"""Unified telemetry subsystem (deequ_tpu/telemetry/): spans, counters,
run listeners, structured export, and repository-persisted operational
records. docs/OBSERVABILITY.md is the user-facing companion."""

import json
import os
import threading

import pytest

from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    Mean,
    Size,
    Uniqueness,
)
from deequ_tpu.telemetry import (
    CollectingRunListener,
    MetricsRegistry,
    Telemetry,
    get_telemetry,
    merge_summaries,
    read_jsonl,
    summarize_phases,
    summary_from_json,
    summary_to_json,
)
from deequ_tpu.telemetry.oprecords import (
    OPERATIONAL_METRICS,
    OperationalAnalyzer,
    operational_metrics,
    operational_values,
)
from fixtures import df_numeric, df_numeric_with_nulls


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------


class TestSpans:
    def test_nesting_and_attributes(self):
        tm = Telemetry(enabled=True, annotate=False)
        finished = []
        tm.add_listener(CollectingRunListener())
        with tm.run("r") as cap:
            with tm.span("outer", phase="x") as outer:
                with tm.span("inner") as inner:
                    inner.set(rows=10)
                assert outer is not inner
        finished = cap.spans
        # children finish first; the run root span closes last
        names = [s["name"] for s in finished]
        assert names == ["inner", "outer", "run:r"]
        inner_rec = finished[0]
        outer_rec = finished[1]
        root_rec = finished[2]
        assert inner_rec["parent_id"] == outer_rec["span_id"]
        assert outer_rec["parent_id"] == root_rec["span_id"]
        assert inner_rec["attributes"] == {"rows": 10}
        assert outer_rec["attributes"] == {"phase": "x"}
        assert all(s["wall_s"] >= 0 for s in finished)

    def test_exception_pops_span(self):
        tm = Telemetry(enabled=True, annotate=False)
        with pytest.raises(ValueError):
            with tm.span("boom"):
                raise ValueError("x")
        assert tm.tracer.current() is None
        # a later span parents correctly (stack not corrupted)
        with tm.run("r") as cap:
            with tm.span("after"):
                pass
        assert cap.spans[0]["name"] == "after"

    def test_thread_safety_parentage(self):
        """Spans on different threads never see each other as parents."""
        tm = Telemetry(enabled=True, annotate=False)
        records = []
        lock = threading.Lock()

        def record(sp):
            with lock:
                records.append(sp.as_record())

        def worker(i):
            with tm.tracer.span(f"outer-{i}", on_finish=record):
                with tm.tracer.span(f"inner-{i}", on_finish=record):
                    pass

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(records) == 16
        by_id = {r["span_id"]: r for r in records}
        for r in records:
            if r["name"].startswith("inner"):
                parent = by_id[r["parent_id"]]
                # the parent is the same-thread outer span
                assert parent["name"] == r["name"].replace(
                    "inner", "outer"
                )
                assert parent["thread"] == r["thread"]
            else:
                assert r["parent_id"] is None

    def test_concurrent_counter_increments(self):
        registry = MetricsRegistry()
        counter = registry.counter("c")

        def bump():
            for _ in range(1000):
                counter.inc()

        threads = [threading.Thread(target=bump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert counter.value == 8000


# --------------------------------------------------------------------------
# disabled path
# --------------------------------------------------------------------------


class TestDisabled:
    def test_noop_identity(self):
        """Disabled spans/captures are SHARED no-op objects — nothing
        allocated, nothing recorded."""
        tm = Telemetry(enabled=False)
        cm1 = tm.span("a")
        cm2 = tm.span("b", attr=1)
        assert cm1 is cm2  # one nullcontext for every disabled span
        with tm.run("r") as cap:
            with tm.span("x"):
                tm.event("scan_phases", host_wait_s=1.0)
        assert cap.summary(tm.metrics.counters_snapshot()) is None
        assert cap.final is None
        assert cap.spans == [] and cap.events == []
        assert tm.recent() == []

    def test_counters_stay_live_when_disabled(self):
        """Counters are the always-on layer (monotonic accounting —
        bench depends on transfer.bytes deltas)."""
        tm = Telemetry(enabled=False)
        tm.counter("transfer.bytes").inc(123)
        assert tm.metrics.counters_snapshot() == {"transfer.bytes": 123}

    def test_disabled_listeners_not_called(self):
        tm = Telemetry(enabled=False)
        listener = tm.add_listener(CollectingRunListener())
        with tm.run("r"):
            tm.event("e")
        tm.analyzer_computed(object(), object())
        tm.check_evaluated(object(), object())
        assert listener.run_starts == []
        assert listener.engine_events == []
        assert listener.analyzers_computed == []
        assert listener.checks_evaluated == []

    def test_disabled_run_still_yields_run_metadata(self):
        """ctx.run_metadata keeps its classic pass timings even with
        telemetry off (the explicit-metadata fallback path)."""
        from deequ_tpu import telemetry

        telemetry.configure(enabled=False)
        try:
            ctx = AnalysisRunner.do_analysis_run(
                df_numeric(), [Size(), Mean("att1")]
            )
        finally:
            telemetry.configure(enabled=True)
        assert ctx.telemetry is None
        assert [p.name for p in ctx.run_metadata.passes] == ["scan"]
        assert ctx.run_metadata.passes[0].wall_s > 0


# --------------------------------------------------------------------------
# serde / export
# --------------------------------------------------------------------------


class TestExport:
    def _run_summary(self):
        tm = Telemetry(enabled=True, annotate=False)
        with tm.run("serde") as cap:
            tm.counter("transfer.bytes").inc(4096)
            with tm.pass_span("scan", rows=100, num_analyzers=2):
                pass
            tm.event(
                "scan_phases", host_wait_s=0.5, put_s=0.25, mode="x"
            )
        return cap.final

    def test_summary_json_round_trip(self):
        summary = self._run_summary()
        assert summary_from_json(summary_to_json(summary)) == summary

    def test_merge_summaries(self):
        a, b = self._run_summary(), self._run_summary()
        merged = merge_summaries([a, None, b])
        assert merged["wall_s"] == pytest.approx(
            a["wall_s"] + b["wall_s"]
        )
        assert len(merged["passes"]) == 2
        assert merged["counters"]["transfer.bytes"] == 8192
        assert merge_summaries([None, None]) is None
        assert merge_summaries([a]) is a

    def test_summarize_phases(self):
        summary = self._run_summary()
        phases = summarize_phases(summary["events"])
        assert phases["host_wait_s"] == pytest.approx(0.5)
        assert phases["put_s"] == pytest.approx(0.25)
        assert phases["scan_passes"] == 1

    def test_jsonl_artifact(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        tm = Telemetry(enabled=True, jsonl_path=path, annotate=False)
        with tm.run("art"):
            with tm.span("step"):
                pass
            tm.event("grouping_spill", columns=["c"], path="device-sort")
        records = read_jsonl(path)
        types = [r["type"] for r in records]
        # inner span, event, the run's own root span, then the summary
        assert types == ["span", "event", "span", "run_summary"]
        span, event, root, run = records
        assert root["name"] == "run:art"
        assert span["name"] == "step"
        assert event["event"] == "grouping_spill"
        assert run["name"] == "art"
        assert run["counters"] == {}
        # every line is plain JSON (the artifact is the CLI's input)
        with open(path) as fh:
            for line in fh:
                json.loads(line)

    def test_prometheus_exposition(self):
        registry = MetricsRegistry()
        registry.counter("transfer.bytes").inc(10)
        registry.gauge("batch.size").set(2048)
        registry.histogram("pass.wall_s").observe(0.02)
        text = registry.to_prometheus()
        assert "# TYPE deequ_tpu_transfer_bytes counter" in text
        assert "deequ_tpu_transfer_bytes 10" in text
        assert "deequ_tpu_batch_size 2048" in text
        assert 'deequ_tpu_pass_wall_s_bucket{le="+Inf"} 1' in text
        assert "deequ_tpu_pass_wall_s_count 1" in text


# --------------------------------------------------------------------------
# run integration: AnalysisRunner / profiler / verification
# --------------------------------------------------------------------------


class TestRunIntegration:
    def test_context_summary_and_wall_consistency(self):
        ctx = AnalysisRunner.do_analysis_run(
            df_numeric(),
            [Size(), Mean("att1"), Completeness("att2"),
             Uniqueness(["item"])],
        )
        summary = ctx.telemetry
        assert summary is not None
        assert [p["pass"] for p in summary["passes"]] == ["scan"]
        # per-pass walls account for (almost) the whole run wall — the
        # acceptance bound is 10%, everything outside a pass is
        # planning overhead
        pass_wall = sum(p["wall_s"] for p in summary["passes"])
        assert pass_wall <= summary["wall_s"]
        assert pass_wall >= 0.5 * summary["wall_s"]
        # run_metadata is derived FROM the summary — identical walls
        assert [p.wall_s for p in ctx.run_metadata.passes] == [
            p["wall_s"] for p in summary["passes"]
        ]
        # engine counters attributed to the run
        assert summary["counters"]["engine.scans"] >= 1
        assert any(
            e["event"] == "scan_phases" for e in summary["events"]
        )
        span_names = {s["name"] for s in summary["spans"]}
        assert "run:analysis" in span_names
        assert "pass:scan" in span_names

    def test_listener_callbacks_across_a_run(self):
        tm = get_telemetry()
        listener = tm.add_listener(CollectingRunListener())
        try:
            AnalysisRunner.do_analysis_run(
                df_numeric(), [Size(), Mean("att1")]
            )
        finally:
            tm.remove_listener(listener)
        assert len(listener.run_starts) == 1
        assert len(listener.run_ends) == 1
        run_id, name, summary = listener.run_ends[0]
        assert name == "analysis" and summary is not None
        assert listener.pass_starts == [("scan", 6, 2)]
        (pname, wall, rows, n) = listener.pass_ends[0]
        assert (pname, rows, n) == ("scan", 6, 2) and wall > 0
        computed = {a for a, _m in listener.analyzers_computed}
        assert computed == {Size(), Mean("att1")}
        assert any(
            e["event"] == "scan_phases" for e in listener.engine_events
        )

    def test_broken_listener_never_fails_the_run(self):
        class Broken(CollectingRunListener):
            def on_pass_end(self, *args):
                raise RuntimeError("dashboard down")

        tm = get_telemetry()
        before = tm.counter("telemetry.listener_errors").value
        listener = tm.add_listener(Broken())
        try:
            ctx = AnalysisRunner.do_analysis_run(
                df_numeric(), [Size()]
            )
        finally:
            tm.remove_listener(listener)
        assert ctx.metric(Size()).value.is_success
        assert tm.counter("telemetry.listener_errors").value > before

    def test_verification_result_carries_telemetry(self):
        from deequ_tpu.checks.check import Check, CheckLevel
        from deequ_tpu.verification.suite import VerificationSuite

        tm = get_telemetry()
        listener = tm.add_listener(CollectingRunListener())
        check = Check(CheckLevel.ERROR, "size").has_size(lambda n: n == 6)
        try:
            result = (
                VerificationSuite()
                .on_data(df_numeric())
                .add_check(check)
                .run()
            )
        finally:
            tm.remove_listener(listener)
        assert result.telemetry is not None
        assert result.run_metadata is not None
        assert len(listener.checks_evaluated) == 1
        assert listener.checks_evaluated[0][0] is check

    def test_profiler_merges_summaries(self):
        from deequ_tpu.profiles.profiler import ColumnProfiler

        profiles = ColumnProfiler.profile(df_numeric())
        assert profiles.telemetry is not None
        # the profiler's passes all fold into one merged summary whose
        # pass list matches the classic run_metadata view
        assert [p["pass"] for p in profiles.telemetry["passes"]] == [
            p.name for p in profiles.run_metadata.passes
        ]


# --------------------------------------------------------------------------
# operational records: the monitor monitors itself
# --------------------------------------------------------------------------


class TestOperationalRecords:
    def test_operational_values_from_summary(self):
        summary = {
            "wall_s": 2.0,
            "passes": [
                {"pass": "scan", "wall_s": 1.5, "rows": 1000,
                 "num_analyzers": 3}
            ],
            "counters": {
                "transfer.bytes": 8000,
                "engine.plan_cache.hits": 1,
                "engine.traces": 2,
                "grouping.spill.device-sort": 1,
                "grouping.spill.host-arrow": 2,
            },
        }
        values = operational_values(summary)
        assert values["rows"] == 1000
        assert values["rows_per_sec"] == pytest.approx(500.0)
        assert values["bytes_per_row"] == pytest.approx(8.0)
        assert values["spill_events"] == 3
        assert values["plan_cache_hits"] == 1
        assert operational_values(None) == {}
        for name in values:
            assert name in OPERATIONAL_METRICS

    def test_repository_round_trip_and_anomaly_series(self, tmp_path):
        """Operational records persist under the run's ResultKey
        through the FILE repository (full serde) and feed an anomaly
        strategy as an ordinary metric series."""
        from deequ_tpu.anomalydetection.base import (
            AnomalyDetector,
            DataPoint,
        )
        from deequ_tpu.anomalydetection.strategies import (
            SimpleThresholdStrategy,
        )
        from deequ_tpu.repository.base import ResultKey
        from deequ_tpu.repository.fs import FileSystemMetricsRepository

        repo = FileSystemMetricsRepository(
            str(tmp_path / "metrics.json")
        )
        for day in (1000, 2000, 3000):
            (
                AnalysisRunner.on_data(df_numeric())
                .add_analyzers([Size(), Mean("att1")])
                .use_repository(repo)
                .save_or_append_result(
                    ResultKey.of(day, {"dataset": "numeric"})
                )
                .run()
            )

        analyzer = OperationalAnalyzer("rows_per_sec")
        records = (
            repo.load()
            .for_analyzers([analyzer])
            .get_success_metrics_as_records()
        )
        assert len(records) == 3
        assert all(r["name"] == "Operational" for r in records)
        assert all(r["instance"] == "rows_per_sec" for r in records)
        assert all(r["value"] > 0 for r in records)
        assert all(r["entity"] == "Dataset" for r in records)

        # the series drives anomaly detection with zero new machinery
        series = [
            DataPoint(r["dataset_date"], r["value"]) for r in records
        ]
        detector = AnomalyDetector(SimpleThresholdStrategy(lower_bound=0.0))
        ok = detector.is_new_point_anomalous(
            series, DataPoint(4000, series[-1].metric_value)
        )
        bad = detector.is_new_point_anomalous(
            series, DataPoint(4000, -1.0)
        )
        assert not ok.is_anomalous
        assert bad.is_anomalous

    def test_returned_context_stays_clean(self, tmp_path):
        """Operational records go to the REPOSITORY only; the returned
        context (user-visible metrics) is unchanged."""
        from deequ_tpu.repository.base import (
            InMemoryMetricsRepository,
            ResultKey,
        )

        repo = InMemoryMetricsRepository()
        key = ResultKey.of(1, {})
        ctx = (
            AnalysisRunner.on_data(df_numeric())
            .add_analyzers([Size()])
            .use_repository(repo)
            .save_or_append_result(key)
            .run()
        )
        assert not any(
            isinstance(a, OperationalAnalyzer) for a in ctx.metric_map
        )
        saved = repo.load_by_key(key).analyzer_context
        assert any(
            isinstance(a, OperationalAnalyzer) for a in saved.metric_map
        )

    def test_operational_analyzer_never_computes(self):
        from deequ_tpu.analyzers.base import MetricCalculationException

        with pytest.raises(MetricCalculationException):
            OperationalAnalyzer("wall_s").compute_metric_from_state(None)
        assert operational_metrics(None) == {}


# --------------------------------------------------------------------------
# tools: obs_report + lint
# --------------------------------------------------------------------------


class TestTools:
    def test_obs_report_renders_real_artifact(self, tmp_path):
        from deequ_tpu import telemetry
        from tools.obs_report import main as report_main

        path = str(tmp_path / "runs.jsonl")
        telemetry.configure(jsonl_path=path)
        try:
            AnalysisRunner.do_analysis_run(
                df_numeric(), [Size(), Mean("att1")]
            )
        finally:
            telemetry.configure(jsonl_path=None)
        assert report_main([path]) == 0
        assert report_main([path, "--counters"]) == 0

    def test_obs_report_render_content(self, tmp_path, capsys):
        from deequ_tpu import telemetry
        from tools.obs_report import main as report_main

        path = str(tmp_path / "runs.jsonl")
        telemetry.configure(jsonl_path=path)
        try:
            AnalysisRunner.do_analysis_run(
                df_numeric(), [Size(), Uniqueness(["att1"])]
            )
        finally:
            telemetry.configure(jsonl_path=None)
        report_main([path])
        out = capsys.readouterr().out
        assert "run " in out and "(analysis)" in out
        assert "scan" in out
        assert "counters (delta over run):" in out
        assert "engine.scans" in out

    def test_obs_report_egress_line(self, tmp_path, capsys):
        """A row-level-sink run renders the egress line: rows split,
        bytes/row out, encode share (docs/EGRESS.md)."""
        from deequ_tpu import telemetry
        from deequ_tpu.checks import Check, CheckLevel
        from deequ_tpu.egress import RowLevelSink
        from deequ_tpu.verification.suite import VerificationSuite
        from tools.obs_report import main as report_main

        path = str(tmp_path / "runs.jsonl")
        telemetry.configure(jsonl_path=path)
        try:
            VerificationSuite.do_verification_run(
                df_numeric_with_nulls(),
                [Check(CheckLevel.ERROR, "c").is_complete("att1")],
                row_level_sink=RowLevelSink(str(tmp_path / "egress")),
            )
        finally:
            telemetry.configure(jsonl_path=None)
        report_main([path])
        out = capsys.readouterr().out
        assert "egress: " in out
        assert "clean /" in out and "quarantined" in out
        assert "bytes/row out" in out
        assert "encode share" in out

    def test_hot_paths_have_no_adhoc_timing(self):
        """The lint satellite: every clock/trace call outside
        deequ_tpu/telemetry/ is a violation."""
        from tools.telemetry_lint import find_violations

        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        assert find_violations(root) == []

    def test_lint_catches_a_violation(self, tmp_path):
        from tools.telemetry_lint import find_violations

        bad = tmp_path / "deequ_tpu" / "engine"
        bad.mkdir(parents=True)
        (bad / "rogue.py").write_text(
            "import time\n"
            "# perf_counter in a comment is fine\n"
            "t0 = time.perf_counter()\n"
        )
        violations = find_violations(str(tmp_path))
        assert violations == [
            ("deequ_tpu/engine/rogue.py", 3, "perf_counter")
        ]

    def test_lint_service_bans_direct_time(self, tmp_path):
        """PR 7 rule: service modules run on injected clocks only —
        time.time / time.sleep are violations there (and only there:
        the same tokens in a non-service module stay legal)."""
        from tools.telemetry_lint import find_violations

        bad = tmp_path / "deequ_tpu" / "service"
        bad.mkdir(parents=True)
        (bad / "rogue.py").write_text(
            "import time\n"
            "# time.time in a comment is fine\n"
            "now = time.time()\n"
            "time.sleep(1)\n"
        )
        elsewhere = tmp_path / "deequ_tpu" / "repository"
        elsewhere.mkdir(parents=True)
        (elsewhere / "fine.py").write_text("import time\nt = time.time()\n")
        violations = find_violations(str(tmp_path))
        assert ("deequ_tpu/service/rogue.py", 3, "time.time") in violations
        assert ("deequ_tpu/service/rogue.py", 4, "sleep") in violations
        assert all("fine.py" not in rel for rel, _l, _t in violations)

    def test_lint_service_bans_admission_bypass(self, tmp_path):
        """PR 7 rule: the service must reach the engine through the
        runner's admission layer — a direct run_scan reference in a
        service module flags."""
        from tools.telemetry_lint import find_violations

        bad = tmp_path / "deequ_tpu" / "service"
        bad.mkdir(parents=True)
        (bad / "rogue.py").write_text(
            "def go(engine, ds, pairs):\n"
            "    return engine.run_scan(ds, pairs)\n"
        )
        violations = find_violations(str(tmp_path))
        assert ("deequ_tpu/service/rogue.py", 2, "run_scan") in violations

    def test_lint_real_service_package_is_clean(self):
        """The shipped service package obeys its own rules."""
        from tools.telemetry_lint import find_violations

        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        service = [
            v for v in find_violations(root)
            if v[0].startswith("deequ_tpu/service/")
        ]
        assert service == []


# --------------------------------------------------------------------------
# end-to-end run tracing (docs/OBSERVABILITY.md "Tracing")
# --------------------------------------------------------------------------


def _traced_child(payload):
    """Spawn-child entry point (module level: pickled by reference)."""
    from deequ_tpu.telemetry import get_telemetry

    with get_telemetry().span("child_work"):
        return payload


def _traced_crash_child(payload):
    """Emits one span (streamed back over the pipe), then dies hard —
    the parent must still know where the child got to."""
    import signal

    from deequ_tpu.telemetry import get_telemetry
    from deequ_tpu.testing.faults import hard_crash

    with get_telemetry().span("doomed_stage"):
        pass
    hard_crash(signal.SIGSEGV)


class _SpanSink:
    """Capture every finished span record on the process telemetry."""

    def __init__(self):
        self.records = []
        self._tm = get_telemetry()

    def __enter__(self):
        self._tm.add_span_sink(self.records.append)
        return self.records

    def __exit__(self, *exc):
        self._tm.remove_span_sink(self.records.append)


def _assert_single_connected_tree(records, trace_id):
    """Every span of the trace reaches ONE root (the synthetic
    ``ticket`` root or the context's reserved root id)."""
    spans = [r for r in records if r.get("trace_id") == trace_id]
    assert spans, f"no spans for trace {trace_id}"
    ids = {r["span_id"] for r in spans}
    roots = [r for r in spans if r.get("parent_id") not in ids]
    assert len(roots) == 1, [(r["name"], r["parent_id"]) for r in roots]
    return spans, roots[0]


class TestRunTracing:
    def test_trace_context_roundtrip(self):
        from deequ_tpu.telemetry import TraceContext

        ctx = TraceContext.mint("run-7", process="host-a")
        assert ctx.trace_id.startswith("run-7-")
        back = TraceContext.decode(ctx.child(123).encode())
        assert back == TraceContext(ctx.trace_id, 123, process="host-a")
        assert TraceContext.decode("garbage") is None
        assert TraceContext.decode("t:notanint:p") is None

    def test_spawn_child_spans_reroot_connected(self):
        """A span emitted INSIDE the spawn child streams back and lands
        under the parent's launching span — one connected tree, child
        spans process-tagged for the fleet timeline."""
        from deequ_tpu.engine.subproc import IsolatedRunner
        from deequ_tpu.telemetry import TraceContext

        tm = get_telemetry()
        ctx = TraceContext.mint("iso-run")
        with _SpanSink() as records:
            with tm.trace_scope(ctx):
                with tm.span("lease_wait"):
                    out = IsolatedRunner(key="trace-ok", use_breaker=False).run(
                        _traced_child, {"x": 1}
                    )
            tm.emit_span(
                "ticket", 0.5, trace=ctx, span_id=ctx.span_id, parent_id=None
            )
        assert out == {"x": 1}
        spans, root = _assert_single_connected_tree(records, ctx.trace_id)
        assert root["name"] == "ticket"
        by_name = {r["name"]: r for r in spans}
        assert by_name["lease_wait"]["parent_id"] == root["span_id"]
        child = by_name["child_work"]
        assert child["process"] == "child"
        # the child's run span parents under the parent's lease span
        run_span = by_name["run:isolated_child"]
        assert run_span["parent_id"] == by_name["lease_wait"]["span_id"]
        assert child["parent_id"] in {r["span_id"] for r in spans}

    def test_crashed_child_streams_spans_before_death(self):
        """Satellite pin: spans that arrived before a SIGSEGV are
        replayed into the parent's tree — trace_report can show where
        the run died."""
        from deequ_tpu.engine.subproc import CrashLoopError, IsolatedRunner
        from deequ_tpu.telemetry import TraceContext

        tm = get_telemetry()
        ctx = TraceContext.mint("crash-run")
        with _SpanSink() as records:
            with tm.trace_scope(ctx):
                with tm.span("lease_wait"):
                    with pytest.raises(CrashLoopError):
                        IsolatedRunner(
                            key="trace-crash",
                            max_relaunches=1,
                            use_breaker=False,
                        ).run(_traced_crash_child, {})
            tm.emit_span(
                "ticket", 0.5, trace=ctx, span_id=ctx.span_id, parent_id=None
            )
        spans, root = _assert_single_connected_tree(records, ctx.trace_id)
        doomed = [r for r in spans if r["name"] == "doomed_stage"]
        assert len(doomed) == 1
        assert doomed[0]["process"] == "child"

    def test_member_provenance_under_coalescing(self):
        """Each coalesced member's sliced result carries telemetry
        scoped to its OWN trace_id — summary and every span record."""
        from deequ_tpu.checks.check import Check, CheckLevel
        from deequ_tpu.data import Dataset
        from deequ_tpu.service import (
            Priority,
            RunRequest,
            VerificationService,
        )

        def _suite(i):
            check = Check(CheckLevel.ERROR, f"tenant-{i}").is_complete(
                "att1"
            )
            if i % 2 == 0:
                check = check.is_complete("att2")
            return [check]

        svc = VerificationService(
            workers=1,
            coalesce=True,
            coalesce_window_s=0.0,
            trace=True,
        )
        handles = [
            svc.submit(
                RunRequest(
                    tenant=f"t{i}",
                    checks=_suite(i),
                    dataset_key="shared/trace-prov",
                    dataset_factory=df_numeric,
                    priority=Priority.BATCH,
                )
            )
            for i in range(3)
        ]
        svc.start()
        try:
            results = [h.result(timeout=300) for h in handles]
        finally:
            svc.stop(drain=False, timeout=30)
        for handle, result in zip(handles, results):
            summary = result.telemetry
            assert summary is not None
            trace_id = summary["trace_id"]
            assert trace_id.startswith(handle.run_id + "-")
            assert summary["spans"], "member summary lost its spans"
            assert all(
                sp["trace_id"] == trace_id for sp in summary["spans"]
            )
        # three members, three distinct trace identities over ONE scan
        assert len({r.telemetry["trace_id"] for r in results}) == 3


class TestTracingZeroCost:
    def test_trace_scope_is_shared_noop_when_disabled(self):
        from deequ_tpu.telemetry import TraceContext

        tm = Telemetry(enabled=False, annotate=False)
        ctx = TraceContext.mint("x")
        assert tm.trace_scope(ctx) is tm.trace_scope(None)
        assert tm.current_trace() is None

    def test_untraced_run_emits_no_trace_spans(self):
        """Without an ambient TraceContext the engine emits exactly the
        classic span set — no phase/persist/egress spans, no trace_id
        tagging — so tracing-off costs nothing beyond PhaseClock."""
        with _SpanSink() as records:
            AnalysisRunner.do_analysis_run(
                df_numeric(), [Size(), Mean("att1")]
            )
        assert records
        names = {r["name"] for r in records}
        assert not any(n.startswith("phase:") for n in names)
        assert "persist" not in names and "egress" not in names
        assert all(r.get("trace_id") is None for r in records)


class TestTraceReportTool:
    def _span(self, trace, sid, parent, name, wall, start=0.0, **attrs):
        return {
            "type": "span", "trace_id": trace, "span_id": sid,
            "parent_id": parent, "name": name, "wall_s": wall,
            "started_at": start, "thread": "t", "attributes": attrs,
        }

    def _records(self):
        return [
            # slow run: queue-bound (8s of 10s in queue_wait)
            self._span("A", 1, None, "ticket", 10.0, run_id="run-a"),
            self._span("A", 2, 1, "queue_wait", 8.0),
            self._span("A", 3, 1, "execute", 2.0, start=8.0),
            # fast run: execute-bound
            self._span("B", 4, None, "ticket", 4.0, run_id="run-b"),
            self._span("B", 5, 4, "queue_wait", 1.0),
            self._span("B", 6, 4, "execute", 3.0, start=1.0),
        ]

    def test_aggregate_names_dominant_p99_stage(self):
        from tools.trace_report import (
            _Tree,
            aggregate,
            decompose,
            load_traces,
        )

        traces = load_traces(self._records())
        trees = {tid: _Tree(sp) for tid, sp in traces.items()}
        decomps = [decompose(tid, trees) for tid in traces]
        agg = aggregate(decomps)
        assert agg["runs"] == 2
        # p99 is the queue-bound run; the report must blame the queue
        assert agg["p99"]["wall_s"] == 10.0
        assert agg["p99"]["dominant_stage"] == "queue_wait"
        assert agg["p50"]["dominant_stage"] == "finalize"
        # per-run stages sum to the root wall exactly
        for d in decomps:
            assert abs(sum(d["stages"].values()) - d["wall_s"]) < 1e-9

    def test_render_waterfall_and_run_filter(self):
        from tools.trace_report import render

        out = render(self._records())
        assert "ticket" in out and "queue_wait" in out
        assert "aggregate over 2 traced run(s):" in out
        assert "dominant stage: queue_wait" in out
        only_a = render(self._records(), run="run-a")
        assert "run-b" not in only_a
        assert render([], run=None).startswith("no traced spans")

    def test_obs_report_all_and_trace_passthrough(self, tmp_path, capsys):
        from tools.obs_report import main as report_main

        path = tmp_path / "runs.jsonl"
        with path.open("w") as fh:
            for rec in self._records():
                fh.write(json.dumps(rec) + "\n")
        assert report_main([str(path), "--all"]) == 0
        out = capsys.readouterr().out
        assert "aggregate over 2 traced run(s):" in out
        assert report_main([str(path), "--trace", "run-b"]) == 0
        out = capsys.readouterr().out
        assert "run-a" not in out
