"""AST-based static-analysis suite for the deequ_tpu tree.

Importing this package registers the default analyzers (lock
discipline, interrupt safety, trace hazards, plan-key discipline,
wire discipline, and the token rules migrated from
tools.telemetry_lint) on the shared registry. Entry points:

    python -m tools.staticcheck [root] [--json] [--rules a,b] [--all]

and, from tests, :func:`tools.staticcheck.run` — returns the finding
list the tier-1 gate asserts empty. See docs/STATIC_ANALYSIS.md.
"""

from tools.staticcheck.core import (  # noqa: F401
    Analyzer,
    Finding,
    SourceFile,
    all_analyzers,
    all_rules,
    collect_files,
    default_root,
    register,
    run_analyzers,
    summarize,
    to_json,
    unwaived,
)

# importing the analyzer modules registers the default suite
from tools.staticcheck import egressdur as _egressdur  # noqa: F401,E402
from tools.staticcheck import fence as _fence  # noqa: F401,E402
from tools.staticcheck import interrupts as _interrupts  # noqa: F401,E402
from tools.staticcheck import locks as _locks  # noqa: F401,E402
from tools.staticcheck import metricdocs as _metricdocs  # noqa: F401,E402
from tools.staticcheck import plankey as _plankey  # noqa: F401,E402
from tools.staticcheck import preempt as _preempt  # noqa: F401,E402
from tools.staticcheck import procs as _procs  # noqa: F401,E402
from tools.staticcheck import threads as _threads  # noqa: F401,E402
from tools.staticcheck import tokens as _tokens  # noqa: F401,E402
from tools.staticcheck import trace as _trace  # noqa: F401,E402
from tools.staticcheck import wire_discipline as _wire_discipline  # noqa: F401,E402

run = run_analyzers
