"""Scan-sharing regression: the reference asserts N scan-shareable
analyzers trigger exactly ONE aggregation job by counting Spark jobs
(SparkMonitor; SURVEY.md §4). The TPU equivalent: count compilations of
the fused update — many analyzers, many batches, ONE trace."""

from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.engine import AnalysisEngine
from fixtures import big_numeric


def test_one_compile_for_many_analyzers_and_batches():
    engine = AnalysisEngine(batch_size=16_384)  # 100k rows -> 7 batches
    analyzers = [
        Size(),
        Completeness("x"),
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        Mean("y"),
        Maximum("y"),
    ]
    context = AnalysisRunner.do_analysis_run(
        big_numeric(), analyzers, engine=engine
    )
    assert all(m.value.is_success for m in context.metric_map.values())
    # ONE fused computation for 9 analyzers over 7 batches
    assert engine.trace_count == 1


def test_batched_equals_single_batch():
    data = big_numeric()
    analyzers = [Mean("x"), StandardDeviation("x"), Minimum("x"), Sum("y")]
    ctx_one = AnalysisRunner.do_analysis_run(
        data, analyzers, engine=AnalysisEngine()
    )
    ctx_many = AnalysisRunner.do_analysis_run(
        data, analyzers, engine=AnalysisEngine(batch_size=4_096)
    )
    for analyzer in analyzers:
        a = ctx_one.metric(analyzer).value.get()
        b = ctx_many.metric(analyzer).value.get()
        assert abs(a - b) < 1e-8 * max(1.0, abs(a)), analyzer


class TestRunMetadata:
    """Per-pass wall-time metadata (SURVEY.md §5.1: an observability
    hook the reference lacks)."""

    def test_runner_records_passes(self):
        import numpy as np

        from deequ_tpu import Dataset, Completeness, Mean, Uniqueness
        from deequ_tpu.analyzers import AnalysisRunner

        ds = Dataset.from_pydict({"x": list(np.arange(1000.0))})
        ctx = AnalysisRunner.do_analysis_run(
            ds, [Completeness("x"), Mean("x"), Uniqueness("x")]
        )
        meta = ctx.run_metadata
        assert meta is not None
        names = [p.name for p in meta.passes]
        assert names == ["scan", "grouping"]
        for p in meta.passes:
            assert p.wall_s > 0 and p.rows == 1000
        assert meta.passes[0].num_analyzers == 2
        assert meta.total_wall_s > 0
        assert meta.as_records()[0]["pass"] == "scan"

    def test_verification_result_carries_metadata(self):
        import numpy as np

        from deequ_tpu import (
            Check,
            CheckLevel,
            Dataset,
            VerificationSuite,
        )

        ds = Dataset.from_pydict({"x": list(np.arange(100.0))})
        result = (
            VerificationSuite()
            .on_data(ds)
            .add_check(
                Check(CheckLevel.ERROR, "m").has_mean("x", lambda m: m > 0)
            )
            .run()
        )
        assert result.run_metadata is not None
        assert result.run_metadata.passes

    def test_profiler_aggregates_pass_timings(self):
        import numpy as np

        from deequ_tpu import Dataset
        from deequ_tpu.profiles.profiler import ColumnProfiler

        ds = Dataset.from_pydict(
            {"x": list(np.arange(500.0)), "c": ["a", "b"] * 250}
        )
        profiles = ColumnProfiler.profile(ds)
        meta = profiles.run_metadata
        assert meta is not None
        # fused pass 1 (generic + native-numeric stats) + histogram pass
        # (native numeric stats ride pass 1; a separate numeric pass
        # only exists for promoted string columns)
        names = [p.name for p in meta.passes]
        assert names == ["scan", "grouping"]
