"""Exactness-golden loader: every case in tests/goldens/*.json must
reproduce its frozen expected value EXACTLY (SURVEY.md §7 hard part 4
— "tests must pin exact values vs reference semantics … else every
metric silently drifts").

The golden file is the semantic contract: nulls, literal NaN, -0.0,
COUNT(col) vs COUNT(*), empty tables, single rows, all-null columns.
Regenerating it is a deliberate act (``python tools/make_goldens.py``)
whose diff must be reviewed — a failure here means the implementation
drifted, not that the golden needs refreshing.
"""

import json
import os
import sys

import pytest

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from deequ_tpu import Dataset  # noqa: E402
from tools import goldens_spec as spec  # noqa: E402

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "goldens", "core_v1.json"
)


def _golden():
    with open(GOLDEN_PATH) as f:
        return json.load(f)


def _case_id(case):
    a = dict(case["analyzer"])
    t = a.pop("type")
    rest = ",".join(f"{k}={v}" for k, v in sorted(a.items()))
    return f"{case['fixture']}-{t}({rest})"


GOLDEN = _golden()


def test_golden_version_and_coverage():
    assert GOLDEN["version"] == spec.GOLDEN_VERSION
    # the frozen file covers exactly the spec's cases — a spec case
    # without a frozen value is an unpinned semantic
    frozen = {
        (c["fixture"], json.dumps(c["analyzer"], sort_keys=True))
        for c in GOLDEN["cases"]
    }
    current = {
        (f, json.dumps(s, sort_keys=True)) for f, s in spec.cases()
    }
    assert frozen == current, (
        "spec cases and frozen golden diverge — regenerate via "
        "tools/make_goldens.py and review the diff"
    )


@pytest.mark.parametrize(
    "case", GOLDEN["cases"], ids=[_case_id(c) for c in GOLDEN["cases"]]
)
def test_golden_case(case):
    tables = spec.fixtures()
    ds = Dataset.from_arrow(tables[case["fixture"]])
    got = spec.run_case(ds, case["analyzer"])
    assert got == case["expect"], (
        f"semantic drift on {_case_id(case)}: frozen="
        f"{case['expect']} got={got}"
    )
