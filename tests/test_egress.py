"""Streaming row-level egress (docs/EGRESS.md): the clean/quarantine
parquet split written DURING the fused scan must be bit-equal to the
in-memory oracle (``verification/rowlevel.py``) — per constraint, per
row — on the resident, streaming and mesh paths, under both
filtered-row semantics; quarantined-batch degradation folds into the
SAME artifact with provenance; and the pass accounting is honest
(``engine.data_passes == 1`` for scan-only suites, ``2`` when a
deferred family forces the oracle's second look).
"""

import json
import os
import types

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu import Check, CheckLevel, config
from deequ_tpu.analyzers import Completeness, Mean, Size, Uniqueness
from deequ_tpu.data import Dataset
from deequ_tpu.egress import BATCH_QUARANTINED, RowLevelSink
from deequ_tpu.engine.resilience import RetryPolicy
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.testing.faults import FaultInjectingDataset
from deequ_tpu.verification.rowlevel import row_level_results
from deequ_tpu.verification.suite import VerificationSuite

NO_SLEEP = RetryPolicy(max_attempts=1, sleep=lambda s: None)

#: forces the resident chunk cache / the streaming wire respectively
RESIDENT = {"device_cache_bytes": 1 << 30}
STREAMING = {"device_cache_bytes": 0}


def _make_data(n=1000, seed=7) -> Dataset:
    rng = np.random.default_rng(seed)
    v = rng.integers(0, 120, size=n)
    s = [
        None if rng.random() < 0.08 else f"u{int(x):03d}@ex.com"
        for x in rng.integers(0, 40, size=n)
    ]
    u = rng.integers(0, n // 2, size=n)  # guaranteed duplicates
    return Dataset.from_pydict(
        {"v": v.tolist(), "s": s, "u": u.tolist()}
    )


def _scan_checks():
    """Mask/predicate + pattern + traceable asserted-value: every
    family that rides the scan (one pass, no deferred phase)."""
    return [
        Check(CheckLevel.ERROR, "scan families")
        .is_complete("s")
        .satisfies("v < 90", "v_small")
        .where("v >= 10")
        .has_pattern("s", r"@ex\.com$")
        .has_min("v", lambda x: x >= 0)
    ]


def _full_checks():
    """Scan families plus Uniqueness — the always-deferred family."""
    return _scan_checks() + [
        Check(CheckLevel.WARNING, "deferred").is_unique("u")
    ]


def _read_artifact(report):
    """Concatenate the split back in source order and sanity-check the
    partitioning invariant: clean + quarantined == input, disjoint."""
    clean = pq.read_table(
        os.path.join(report.clean_dir, "part-00000.parquet")
    )
    quarantine = pq.read_table(
        os.path.join(report.quarantine_dir, "part-00000.parquet")
    )
    shared = [
        c for c in clean.schema.names if c in set(quarantine.schema.names)
    ]
    merged = pa.concat_tables(
        [clean.select(shared), quarantine.select(shared)]
    )
    order = np.argsort(
        np.asarray(merged.column("__row_index__").to_pylist())
    )
    merged = merged.take(pa.array(order))
    idx = merged.column("__row_index__").to_pylist()
    assert idx == list(range(report.rows_total))
    return clean, quarantine, merged


def _run_with_sink(data, checks, tmp_path, outcome="true", engine=None,
                   columns=None):
    sink = RowLevelSink(
        str(tmp_path / "egress"),
        filtered_row_outcome=outcome,
        columns=columns,
        tenant="acme",
        run_id="r1",
    )
    result = VerificationSuite.do_verification_run(
        data, checks, engine=engine, row_level_sink=sink
    )
    return result, result.row_level_egress


class TestDifferentialAgainstOracle:
    """Satellite 1: the streamed artifact equals the in-memory oracle,
    column for column, row for row."""

    @pytest.mark.parametrize("mode", ["resident", "streaming"])
    @pytest.mark.parametrize("outcome", ["true", "null"])
    def test_bit_equal_outcomes(self, tmp_path, mode, outcome):
        data = _make_data()
        cfg = RESIDENT if mode == "resident" else STREAMING
        with config.configure(batch_size=104, **cfg):
            result, report = _run_with_sink(
                data, _full_checks(), tmp_path, outcome=outcome
            )
        assert report.status == "complete"
        assert report.rows_clean + report.rows_quarantined == 1000
        assert set(report.constraints.values()) == {"scan", "deferred"}
        oracle = row_level_results(
            result.check_results, data, filtered_row_outcome=outcome
        ).table
        _, _, merged = _read_artifact(report)
        assert len(oracle.schema.names) >= 5
        for name in oracle.schema.names:
            assert (
                merged.column(name).to_pylist()
                == oracle.column(name).to_pylist()
            ), f"outcome column diverged: {name} ({mode}/{outcome})"

    def test_clean_rows_pass_everything(self, tmp_path):
        data = _make_data()
        with config.configure(batch_size=104, **STREAMING):
            result, report = _run_with_sink(
                data, _full_checks(), tmp_path
            )
        clean, quarantine, _ = _read_artifact(report)
        oracle = row_level_results(result.check_results, data).table
        for name in oracle.schema.names:
            assert all(clean.column(name).to_pylist())
        # every quarantined row fails at least one constraint, and
        # says which
        labels = quarantine.column("__failed_constraints__").to_pylist()
        assert all(labels)
        fail_any = np.zeros(len(quarantine), dtype=bool)
        for name in oracle.schema.names:
            col = quarantine.column(name).to_pylist()
            fail_any |= np.array([x is False for x in col])
        assert fail_any.all()

    def test_failed_row_counts_match_aggregate_metrics(self, tmp_path):
        """Satellite 1: per-constraint failed-row counts are the same
        numbers the aggregate metrics report."""
        n = 1000
        data = _make_data(n)
        checks = [
            Check(CheckLevel.ERROR, "agg")
            .is_complete("s")
            .satisfies("v < 90", "v_small")
            .is_unique("u")
        ]
        with config.configure(batch_size=104, **STREAMING):
            result, report = _run_with_sink(data, checks, tmp_path)
        _, _, merged = _read_artifact(report)

        def failed(fragment):
            (name,) = [
                c for c in merged.schema.names if fragment in c
            ]
            col = merged.column(name).to_pylist()
            return sum(1 for x in col if x is False)

        metrics = {
            type(a).__name__: m.value.get()
            for a, m in result.metrics.items()
        }
        assert failed("Completeness") == n - round(
            metrics["Completeness"] * n
        )
        assert failed("v_small") == n - round(metrics["Compliance"] * n)
        assert failed("Uniqueness") == n - round(
            metrics["Uniqueness"] * n
        )

    def test_scan_only_suite_is_one_pass(self, tmp_path):
        """Acceptance criterion: mask/predicate suites stream the split
        in the SAME single pass the metrics ride."""
        data = _make_data()
        tm = get_telemetry()
        with config.configure(batch_size=104, **STREAMING):
            before = tm.counter("engine.data_passes").value
            _, report = _run_with_sink(data, _scan_checks(), tmp_path)
            delta = tm.counter("engine.data_passes").value - before
        assert delta == 1
        assert set(report.constraints.values()) == {"scan"}

    def test_deferred_suite_is_honestly_two_passes(self, tmp_path):
        data = _make_data()
        tm = get_telemetry()
        with config.configure(batch_size=104, **STREAMING):
            before = tm.counter("engine.data_passes").value
            _, report = _run_with_sink(data, _full_checks(), tmp_path)
            delta = tm.counter("engine.data_passes").value - before
        assert delta == 2
        assert "deferred" in report.constraints.values()

    def test_mesh_path_matches_oracle(self, tmp_path, cpu_mesh):
        data = _make_data(600)
        engine = AnalysisEngine(mesh=cpu_mesh)
        with config.configure(batch_size=104, **STREAMING):
            result, report = _run_with_sink(
                data, _full_checks(), tmp_path, engine=engine
            )
        oracle = row_level_results(result.check_results, data).table
        _, _, merged = _read_artifact(report)
        for name in oracle.schema.names:
            assert (
                merged.column(name).to_pylist()
                == oracle.column(name).to_pylist()
            )

    def test_column_projection_and_provenance(self, tmp_path):
        data = _make_data()
        with config.configure(batch_size=104, **STREAMING):
            _, report = _run_with_sink(
                data, _scan_checks(), tmp_path, columns=["v"]
            )
        clean, quarantine, _ = _read_artifact(report)
        for split in (clean, quarantine):
            names = set(split.schema.names)
            assert "v" in names and "s" not in names and "u" not in names
            assert {"__row_index__", "__batch_seq__"} <= names
        # the heavier provenance is quarantine-only: the clean split
        # stays lean (docs/EGRESS.md)
        assert {
            "__failed_constraints__",
            "__error_class__",
            "__tenant__",
            "__run_id__",
        } <= set(quarantine.schema.names)
        assert set(quarantine.column("__tenant__").to_pylist()) <= {"acme"}
        manifest = json.loads(
            open(report.manifest_path, encoding="utf-8").read()
        )
        assert manifest["status"] == "complete"


class TestDegradationFoldIn:
    """Acceptance criterion: quarantined-batch degradation (PR 3) folds
    into the SAME egress artifact — whole failed units land in the
    quarantine split with BatchFailure provenance and NULL outcomes."""

    @pytest.mark.parametrize("mode", ["resident", "streaming"])
    def test_failed_unit_lands_in_quarantine(self, tmp_path, mode):
        n = 1000
        data = FaultInjectingDataset(
            _make_data(n), permanent={3}
        )
        cfg = RESIDENT if mode == "resident" else STREAMING
        with config.configure(
            batch_size=104, scan_retry=NO_SLEEP, **cfg
        ):
            result, report = _run_with_sink(
                data, _scan_checks(), tmp_path
            )
        assert report.status == "complete"
        clean, quarantine, _ = _read_artifact(report)
        labels = quarantine.column("__failed_constraints__").to_pylist()
        failed_rows = [
            i
            for i, lab in zip(
                quarantine.column("__row_index__").to_pylist(), labels
            )
            if lab == BATCH_QUARANTINED
        ]
        # batch 3 = rows 312..415; both granularities cover it whole
        assert set(range(312, 416)) <= set(failed_rows)
        err = {
            lab: ec
            for lab, ec in zip(
                labels, quarantine.column("__error_class__").to_pylist()
            )
        }
        assert err[BATCH_QUARANTINED] == "ValueError"
        # outcome columns are NULL on quarantined-batch rows: the scan
        # never produced their bits
        for name in report.constraints:
            col = quarantine.column(name).to_pylist()
            for i, lab in enumerate(labels):
                if lab == BATCH_QUARANTINED:
                    assert col[i] is None
        # the manifest carries the same provenance the degradation
        # record reports
        manifest = json.loads(
            open(report.manifest_path, encoding="utf-8").read()
        )
        assert manifest["scan_failures"], manifest
        assert (
            manifest["scan_failures"][0]["error_class"] == "ValueError"
        )
        assert result.degradation is not None


class TestPlanningAndLimits:
    def test_no_row_level_constraints_reports_and_skips(self, tmp_path):
        data = _make_data(100)
        checks = [
            Check(CheckLevel.ERROR, "agg only").has_size(
                lambda s: s == 100
            )
        ]
        sink = RowLevelSink(str(tmp_path / "egress"))
        result = VerificationSuite.do_verification_run(
            data, checks, row_level_sink=sink
        )
        report = result.row_level_egress
        assert report is sink.report
        assert report.status == "no_row_level_constraints"
        assert not os.path.exists(str(tmp_path / "egress" / "clean"))

    def test_checkpointer_composition_now_runs(self, tmp_path):
        """Regression for the lifted refusal (docs/EGRESS.md "Durable
        egress"): plan_row_sink + a checkpointing engine no longer
        raises — the composed run completes, checkpoints durably
        mid-scan, and the artifact still matches the oracle."""
        from deequ_tpu.io.state_provider import ScanCheckpointer

        data = _make_data()
        engine = AnalysisEngine(
            checkpointer=ScanCheckpointer(str(tmp_path / "ckpt"))
        )
        tm = get_telemetry()
        before = tm.counter("engine.checkpoints_written").value
        with config.configure(
            batch_size=104, checkpoint_every_batches=3, **STREAMING
        ):
            result, report = _run_with_sink(
                data, _scan_checks(), tmp_path, engine=engine
            )
        assert report.status == "complete"
        assert tm.counter("engine.checkpoints_written").value > before
        oracle = row_level_results(result.check_results, data).table
        _, _, merged = _read_artifact(report)
        for name in oracle.schema.names:
            assert (
                merged.column(name).to_pylist()
                == oracle.column(name).to_pylist()
            )

    def test_bad_filtered_row_outcome_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="filtered_row_outcome"):
            RowLevelSink(str(tmp_path / "e"), filtered_row_outcome="drop")


class TestServiceIntegration:
    """The sink is per-run state: service runs carrying one never
    coalesce (they do ride crash isolation now — the spawn child
    writes the artifact dir directly; tests/test_egress_durability.py
    drives that path)."""

    def test_sink_runs_refuse_to_coalesce(self):
        from deequ_tpu.service.coalesce import CoalescePolicy
        from deequ_tpu.service.queue import Priority

        policy = CoalescePolicy(enabled=True)
        sinkful = types.SimpleNamespace(
            payload=types.SimpleNamespace(row_level_sink=object()),
            handle=types.SimpleNamespace(priority=Priority.BATCH),
        )
        sinkless = types.SimpleNamespace(
            payload=types.SimpleNamespace(row_level_sink=None),
            handle=types.SimpleNamespace(priority=Priority.BATCH),
        )
        assert not policy.may_coalesce(sinkful)
        assert policy.may_coalesce(sinkless)

    def test_service_run_streams_the_split(self, tmp_path):
        from deequ_tpu.service.service import (
            RunRequest,
            VerificationService,
        )

        data = _make_data(500)
        sink = RowLevelSink(str(tmp_path / "egress"))
        svc = VerificationService(workers=1).start()
        try:
            with config.configure(batch_size=104, **STREAMING):
                handle = svc.submit(
                    RunRequest(
                        tenant="acme",
                        checks=tuple(_scan_checks()),
                        dataset_key="t",
                        dataset_factory=lambda: data,
                        row_level_sink=sink,
                    )
                )
                assert handle.wait(timeout=60)
                result = handle.result(timeout=0)
        finally:
            svc.stop(drain=False, timeout=10)
        report = result.row_level_egress
        assert report is not None and report.status == "complete"
        _, _, merged = _read_artifact(report)
        assert len(merged) == 500
