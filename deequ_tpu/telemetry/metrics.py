"""Metrics registry: counters, gauges, and latency histograms.

The registry is the ONE place operational counts live (SURVEY.md §5.1:
the reference delegates all of this to the Spark UI; the VLDB'18 paper
frames deequ around metric time series — which should include the
system's *own* operational metrics). Counters are always-on: a counter
bump is one locked integer add per *pass/batch*-granularity event, the
same cost the seed already paid for its ad-hoc ``_TRANSFER_BYTES``
global — only spans/export/listeners are gated by the telemetry
``enabled`` flag (see runtime.py).

Standard instrument names are cataloged in docs/OBSERVABILITY.md; the
conventional ones used by the engine:

- ``transfer.bytes``            host->device bytes shipped (data layer)
- ``engine.scans``              run_scan invocations
- ``engine.plan_cache.hits`` / ``engine.plan_cache.misses``
- ``engine.traces``             fused-update retraces
- ``engine.device_fetches``     packed device_get round trips
- ``engine.vectorize.units`` / ``engine.vectorize.stacked_members``
- ``grouping.spill.<path>``     spill/fallback decisions per path
- ``runner.runs`` / ``runner.analyzer_failures``
- ``repository.saves`` / ``repository.loads``
- ``checks.evaluated``

Every registered name must have a catalog row in docs/OBSERVABILITY.md
(and vice versa) — the ``metric-docs`` staticcheck rule enforces the
pairing in both directions.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, Optional, Sequence, Tuple

# latency buckets (seconds) — wide enough for both a 2ms dispatch and a
# 10-minute streamed pass
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 600.0,
)


class Counter:
    """Monotonic counter. ``inc`` is safe from any thread."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += int(n)

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar (e.g. the batch size a run resolved)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket latency histogram (cumulative, Prometheus-style)."""

    __slots__ = ("name", "buckets", "_counts", "_count", "_sum",
                 "_min", "_max", "_lock")

    def __init__(
        self, name: str, buckets: Optional[Sequence[float]] = None
    ):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += value
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            cumulative = {}
            running = 0
            for bound, n in zip(self.buckets, self._counts):
                running += n
                cumulative[bound] = running
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
                "buckets": cumulative,
            }


def _prom_name(name: str) -> str:
    return "deequ_tpu_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


class MetricsRegistry:
    """Named instrument registry; get-or-create is thread-safe and the
    returned instruments are stable, so hot paths can cache them."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            inst = self._counters.get(name)
            if inst is None:
                inst = self._counters[name] = Counter(name)
            return inst

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            inst = self._gauges.get(name)
            if inst is None:
                inst = self._gauges[name] = Gauge(name)
            return inst

    def histogram(
        self, name: str, buckets: Optional[Sequence[float]] = None
    ) -> Histogram:
        with self._lock:
            inst = self._histograms.get(name)
            if inst is None:
                inst = self._histograms[name] = Histogram(name, buckets)
            return inst

    # -- export ---------------------------------------------------------

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {k: c.value for k, c in sorted(self._counters.items())}

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counters = {k: c.value for k, c in sorted(self._counters.items())}
            gauges = {k: g.value for k, g in sorted(self._gauges.items())}
            histograms = {
                k: h.snapshot() for k, h in sorted(self._histograms.items())
            }
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
        }

    def to_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4) of every instrument."""
        snap = self.snapshot()
        lines = []
        for name, value in snap["counters"].items():
            pname = _prom_name(name)
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {value}")
        for name, value in snap["gauges"].items():
            pname = _prom_name(name)
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {value}")
        for name, h in snap["histograms"].items():
            pname = _prom_name(name)
            lines.append(f"# TYPE {pname} histogram")
            for bound, n in h["buckets"].items():
                lines.append(f'{pname}_bucket{{le="{bound}"}} {n}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {h["count"]}')
            lines.append(f"{pname}_sum {h['sum']}")
            lines.append(f"{pname}_count {h['count']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every instrument (tests only — counters are meant to be
        process-monotonic so deltas can be snapshotted around runs)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
