"""DataType inference analyzer.

Reference: ``analyzers/DataType.scala`` + the ``StatefulDataType``
Catalyst aggregate (SURVEY.md §2.2, §2.3): per-value classification into
{Unknown(null), Fractional, Integral, Boolean, String} buckets, counts
packed into a vector whose merge is elementwise sum.

TPU design (SURVEY.md §2.3 table): the regex classification runs
host-side ONCE over the column *dictionary* (vectorized, small), giving a
code -> bucket lookup table; the device pass is a gather + one-hot
count — a 5-counter psum across the mesh. Numeric/boolean columns
classify from the schema directly (every non-null value already has the
column's type).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.base import (
    Precondition,
    ScanOps,
    ScanShareableAnalyzer,
    has_column,
)
from deequ_tpu.analyzers.basic import _compile_where, _row_mask
from deequ_tpu.analyzers.states import DataTypeHistogram
from deequ_tpu.data.table import ColumnRequest, Dataset, Kind
from deequ_tpu.metrics.distribution import (
    Distribution,
    DistributionValue,
    HistogramMetric,
)
from deequ_tpu.metrics.metric import Entity, Metric
from deequ_tpu.utils.trylike import Success

# Classification regexes (reference: StatefulDataType's patterns)
_INTEGRAL_RE = re.compile(r"^[-+]?\d+$")
_FRACTIONAL_RE = re.compile(r"^[-+]?(\d+\.\d*|\.\d+|\d+)([eE][-+]?\d+)?$")
_BOOLEAN_RE = re.compile(r"^(true|false)$", re.IGNORECASE)

_BUCKET_NAMES = ("Unknown", "Fractional", "Integral", "Boolean", "String")


def classify_string(value: str) -> int:
    if _BOOLEAN_RE.match(value):
        return DataTypeHistogram.BOOLEAN
    if _INTEGRAL_RE.match(value):
        return DataTypeHistogram.INTEGRAL
    if _FRACTIONAL_RE.match(value):
        return DataTypeHistogram.FRACTIONAL
    return DataTypeHistogram.STRING


def counts_from_code_presence(
    codes: "jnp.ndarray",  # (C, B) int codes, -1 = null
    valid: "jnp.ndarray",  # (C, B) validity (row mask pre-ANDed)
    rows: "jnp.ndarray",  # (B,) kept-row mask
    table: "jnp.ndarray",  # (C, D) class LUT per dictionary entry
) -> "jnp.ndarray":
    """(C, 6) type counts for dict-encoded columns WITHOUT per-row
    gathers: per-code counts via a (C, D, B)->(C, D) compare-reduce
    (VPU rate), then a class einsum over the LUT — vs per-row LUT
    gather + scatter-add, both serialized-scatter-class on TPU
    (~5-9x slower measured, docs/PERF.md). Null slot = kept rows
    minus typed rows (a valid row always has a code; invalid/null
    rows match no dictionary slot). The single-analyzer and stacked
    group paths BOTH call this — their states max-merge, so the math
    must stay single-sourced."""
    from deequ_tpu.sketches.hll import tiled_code_presence

    D = table.shape[1]
    cnt = tiled_code_presence(codes, valid, D, count=True)  # (C, D)
    onehot = jax.nn.one_hot(table, 6, dtype=jnp.int32)
    counts = jnp.einsum("cd,cdk->ck", cnt, onehot)
    kept = rows.sum(dtype=jnp.int32)
    nulls = kept - cnt.sum(axis=1, dtype=jnp.int32)
    return counts.at[:, DataTypeHistogram.NULL].add(nulls)


@dataclass(frozen=True)
class DataType(ScanShareableAnalyzer):
    """Inferred-type histogram of a column (reference: DataType.scala)."""

    column: str
    where: Optional[str] = None

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column)]

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        kind = dataset.schema.kind_of(self.column)
        col_req = ColumnRequest(
            self.column, "codes" if kind == Kind.STRING else "mask"
        )
        return [col_req, ColumnRequest(self.column, "mask")] + reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column
        kind = dataset.schema.kind_of(col)
        # the presence fast path shares ONE implementation with the
        # stacked group builder (counts_from_code_presence below):
        # the two produce merge-compatible states, so the math must
        # stay single-sourced

        if kind == Kind.STRING:
            from deequ_tpu.analyzers.base import pad_pow2

            dictionary = dataset.dictionary(col)
            lut = np.zeros(max(len(dictionary), 1), dtype=np.int32)
            for i, value in enumerate(dictionary):
                lut[i] = (
                    DataTypeHistogram.NULL
                    if value is None
                    else classify_string(str(value))
                )

            # LUT as runtime input (pow2-padded): shared compiled scan
            # across datasets — see ScanOps.consts
            def update(
                state: DataTypeHistogram, batch, consts
            ) -> DataTypeHistogram:
                from deequ_tpu.sketches.hll import PRESENCE_DICT_CAP

                table = consts["lut"]
                rows = _row_mask(batch, where_fn)
                valid = batch[f"{col}::mask"] & rows
                codes = batch[f"{col}::codes"]
                if table.shape[0] <= PRESENCE_DICT_CAP:
                    counts = counts_from_code_presence(
                        codes[None, :],
                        valid[None, :],
                        rows,
                        table[None, :],
                    )[0]
                    return DataTypeHistogram(
                        state.counts + counts.astype(jnp.int64)
                    )
                bucket = table[jnp.clip(codes, 0, table.shape[0] - 1)]
                bucket = jnp.where(valid, bucket, DataTypeHistogram.NULL)
                bucket = jnp.where(rows, bucket, 5)  # padding -> reserved
                # i32 scatter, i64 carry: int64 scatters are ~30x
                # slower on TPU (emulated); batch counts fit i32
                counts = jnp.zeros(7, dtype=jnp.int32).at[
                    bucket.astype(jnp.int32)
                ].add(1)[:6]
                new = state.counts + counts.astype(jnp.int64)
                new = new.at[5].set(0)
                return DataTypeHistogram(new)

            return ScanOps(
                DataTypeHistogram.identity,
                update,
                DataTypeHistogram.merge,
                consts={"lut": pad_pow2(lut, DataTypeHistogram.STRING)},
            )
        else:
            static_bucket = {
                Kind.INTEGRAL: DataTypeHistogram.INTEGRAL,
                Kind.FRACTIONAL: DataTypeHistogram.FRACTIONAL,
                Kind.BOOLEAN: DataTypeHistogram.BOOLEAN,
            }.get(kind, DataTypeHistogram.STRING)

            def update(state: DataTypeHistogram, batch) -> DataTypeHistogram:
                rows = _row_mask(batch, where_fn)
                valid = batch[f"{col}::mask"] & rows
                n_valid = jnp.sum(valid, dtype=jnp.int64)
                n_null = jnp.sum(rows & ~valid, dtype=jnp.int64)
                counts = state.counts
                counts = counts.at[static_bucket].add(n_valid)
                counts = counts.at[DataTypeHistogram.NULL].add(n_null)
                return DataTypeHistogram(counts)

        return ScanOps(
            DataTypeHistogram.identity, update, DataTypeHistogram.merge
        )

    def compute_metric_from_state(self, state) -> Metric:
        if state is None:
            state = DataTypeHistogram.identity()
        counts = np.asarray(state.counts)[:5]
        total = int(counts.sum())
        values = {
            name: DistributionValue(
                int(c), (int(c) / total) if total else 0.0
            )
            for name, c in zip(_BUCKET_NAMES, counts)
        }
        dist = Distribution(values, number_of_bins=5)
        return HistogramMetric(
            Entity.COLUMN, "DataType", self.instance, Success(dist)
        )


def inferred_kind(metric: HistogramMetric) -> Kind:
    """Decide a concrete type from the histogram, the way the reference's
    profiler promotes string columns (SURVEY.md §3.3 pass 1->2): any
    String => String; any Fractional => Fractional (integrals embed);
    else Integral / Boolean / Unknown."""
    dist = metric.value.get()
    non_null = {
        k: v.absolute for k, v in dist.values.items() if k != "Unknown"
    }
    total = sum(non_null.values())
    if total == 0:
        return Kind.UNKNOWN
    if non_null.get("String", 0) > 0:
        return Kind.STRING
    if non_null.get("Fractional", 0) > 0:
        if non_null.get("Boolean", 0) > 0:
            return Kind.STRING
        return Kind.FRACTIONAL
    if non_null.get("Boolean", 0) > 0:
        if non_null.get("Integral", 0) > 0:
            return Kind.STRING
        return Kind.BOOLEAN
    return Kind.INTEGRAL
