"""Structured export helpers: summary serde, summary merging, JSONL
artifact reading, the live metrics endpoint, and SLO tracking.

The *summary* is the per-run dict produced by ``RunCapture.summary``
(runtime.py) and attached to ``AnalyzerContext``/``VerificationResult``
— plain JSON-serializable data by construction, so persistence is
``json.dumps``/``loads`` with a round-trip identity (tested in
tests/test_telemetry.py).

:func:`serve_metrics` is the live fleet plane: a stdlib-only HTTP
endpoint exposing the registry's Prometheus text at ``/metrics`` and a
caller-supplied JSON health snapshot at ``/healthz``. Nothing here
starts unless explicitly asked (zero-cost-when-off: no thread, no
socket). :class:`SloTracker` turns the ``service.queue_wait_s.<class>``
histograms into latency-objective attainment and error-budget burn.
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence


def summary_to_json(summary: Dict[str, Any], indent: int = 2) -> str:
    return json.dumps(summary, indent=indent, default=str)


def summary_from_json(text: str) -> Dict[str, Any]:
    return json.loads(text)


def merge_summaries(
    summaries: Sequence[Optional[Dict[str, Any]]],
) -> Optional[Dict[str, Any]]:
    """Fold several per-run summaries (e.g. the profiler's passes over
    the same dataset) into one: walls add, pass/event/span lists
    concatenate in order, counter deltas add. ``None`` entries are
    skipped; all-None means no telemetry was captured."""
    present = [s for s in summaries if s]
    if not present:
        return None
    if len(present) == 1:
        return present[0]
    counters: Dict[str, float] = {}
    for s in present:
        for k, v in s.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
    return {
        "run_id": present[0].get("run_id"),
        "run_ids": [s.get("run_id") for s in present],
        "name": present[0].get("name", "run"),
        "wall_s": sum(s.get("wall_s", 0.0) for s in present),
        "passes": [p for s in present for p in s.get("passes", [])],
        "events": [e for s in present for e in s.get("events", [])],
        "spans": [sp for s in present for sp in s.get("spans", [])],
        "counters": counters,
    }


def summarize_phases(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Sum ``scan_phases`` events into one wall-decomposition dict (the
    shape bench.py and tools/obs_report.py report)."""
    out: Dict[str, Any] = {}
    for e in events:
        if e.get("event") != "scan_phases":
            continue
        for k, v in e.items():
            if isinstance(v, float):
                out[k] = out.get(k, 0.0) + v
        out["scan_passes"] = out.get("scan_passes", 0) + 1
    return {
        k: (round(v, 3) if isinstance(v, float) else v)
        for k, v in out.items()
    }


class MetricsServer:
    """Handle on a running :func:`serve_metrics` endpoint."""

    def __init__(self, httpd: Any, thread: threading.Thread):
        self._httpd = httpd
        self._thread = thread
        self.port: int = httpd.server_address[1]
        self.host: str = httpd.server_address[0]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 — idempotent close
            pass
        self._thread.join(timeout=5.0)


def serve_metrics(
    port: int,
    registry: Optional[Any] = None,
    health: Optional[Callable[[], Dict[str, Any]]] = None,
    host: str = "127.0.0.1",
) -> MetricsServer:
    """Start the live observability endpoint on a daemon thread
    (stdlib ``http.server`` only — no new dependencies):

    - ``GET /metrics`` — Prometheus 0.0.4 text from ``registry``
      (default: the process telemetry's registry)
    - ``GET /healthz`` — ``health()`` rendered as JSON (queue depths,
      slices active, breaker states, shed counts when wired by
      ``VerificationService``); ``{"status": "ok"}`` if no callback
    - ``GET /fleetz`` — the health payload's ``fleet`` section alone
      (lease epoch, peer ages, adoptions, fenced writes — docs/
      SERVICE.md "Fleet failover"); ``{"status": "no fleet"}`` when
      the replica is not a fleet member

    ``port=0`` binds an ephemeral port (read it off the returned
    handle). The caller owns shutdown via ``MetricsServer.close()``.
    """
    import http.server

    if registry is None:
        from deequ_tpu.telemetry.runtime import get_telemetry

        registry = get_telemetry().metrics

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path.split("?", 1)[0] == "/metrics":
                body = registry.to_prometheus().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?", 1)[0] == "/healthz":
                try:
                    payload = health() if health is not None else {
                        "status": "ok"
                    }
                except Exception as exc:  # noqa: BLE001 — a broken
                    # health probe must report, not 500-and-hide
                    payload = {"status": "error", "error": str(exc)}
                body = json.dumps(payload, default=str).encode("utf-8")
                ctype = "application/json"
            elif self.path.split("?", 1)[0] == "/fleetz":
                try:
                    full = health() if health is not None else {}
                    payload = full.get("fleet") or {"status": "no fleet"}
                except Exception as exc:  # noqa: BLE001 — same
                    # report-don't-hide contract as /healthz
                    payload = {"status": "error", "error": str(exc)}
                body = json.dumps(payload, default=str).encode("utf-8")
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr spam
            pass

    httpd = http.server.ThreadingHTTPServer((host, int(port)), _Handler)
    httpd.daemon_threads = True
    thread = threading.Thread(  # lint-ok: thread-discipline: daemon endpoint thread owned by MetricsServer.close(), not scan teardown
        target=httpd.serve_forever,
        name="deequ-tpu-metrics",
        daemon=True,
    )
    thread.start()
    return MetricsServer(httpd, thread)


def parse_slo_objectives(spec: str) -> Dict[str, float]:
    """Parse the ``service_slo_objectives`` config string —
    ``"interactive=1.0,batch=30"`` — into ``{class: seconds}``.
    Malformed pairs are skipped (config must never crash a service)."""
    out: Dict[str, float] = {}
    for pair in (spec or "").split(","):
        pair = pair.strip()
        if not pair or "=" not in pair:
            continue
        key, _, value = pair.partition("=")
        try:
            out[key.strip()] = float(value)
        except ValueError:
            continue
    return out


class SloTracker:
    """Per-class (and optionally per-tenant) latency SLOs over the
    existing ``service.queue_wait_s.<class>`` histograms.

    For each objective the tracker reports *attainment* (the fraction
    of observed waits at or under the objective, resolved conservatively
    against the histogram's bucket bounds) and *error-budget burn*:
    ``(1 - attained) / (1 - target)`` — burn 1.0 means the budget is
    exactly spent, >1 means the objective is being violated faster than
    the target tolerates. Snapshots are plain dicts so they persist as
    oprecords (`telemetry/oprecords.py:slo_metrics`) and serve from
    ``/healthz``.
    """

    def __init__(
        self,
        objectives: Dict[str, float],
        target: float = 0.99,
        registry: Optional[Any] = None,
        prefix: str = "service.queue_wait_s",
    ):
        if registry is None:
            from deequ_tpu.telemetry.runtime import get_telemetry

            registry = get_telemetry().metrics
        self.objectives = dict(objectives)
        self.target = float(target)
        self.registry = registry
        self.prefix = prefix

    def _attainment(self, hist_snap: Dict[str, Any],
                    objective_s: float) -> Dict[str, Any]:
        count = int(hist_snap.get("count", 0))
        buckets = hist_snap.get("buckets", {})
        bounds = sorted(buckets)
        # conservative: observations credited to the objective are the
        # cumulative count at the largest bucket bound <= objective
        idx = bisect.bisect_right(bounds, objective_s) - 1
        within = int(buckets[bounds[idx]]) if idx >= 0 else 0
        attained = (within / count) if count else 1.0
        budget = 1.0 - self.target
        burn = ((1.0 - attained) / budget) if budget > 0 else (
            0.0 if attained >= 1.0 else float("inf")
        )
        return {
            "objective_s": objective_s,
            "count": count,
            "within": within,
            "attained": round(attained, 6),
            "budget_burn": round(burn, 6),
        }

    def snapshot(self) -> Dict[str, Any]:
        """Per-class objectives read ``<prefix>.<class>``; a
        ``tenant:<name>`` objective reads ``<prefix>.tenant.<name>``
        (observed by the scheduler only while SLO tracking is on)."""
        histograms = self.registry.snapshot()["histograms"]
        classes: Dict[str, Any] = {}
        tenants: Dict[str, Any] = {}
        for key, objective_s in sorted(self.objectives.items()):
            if key.startswith("tenant:"):
                name = key.split(":", 1)[1]
                hist = histograms.get(f"{self.prefix}.tenant.{name}")
                bucket_map = tenants
                out_key = name
            else:
                hist = histograms.get(f"{self.prefix}.{key}")
                bucket_map = classes
                out_key = key
            if hist is None:
                hist = {"count": 0, "buckets": {}}
            bucket_map[out_key] = self._attainment(hist, objective_s)
        return {
            "target": self.target,
            "classes": classes,
            "tenants": tenants,
        }

    def tenant_objectives(self) -> Dict[str, float]:
        return {
            key.split(":", 1)[1]: obj
            for key, obj in self.objectives.items()
            if key.startswith("tenant:")
        }


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL artifact (skips unparseable lines — the
    log may be appended by several processes)."""
    records: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records
