"""Scan-coalescing policy: which queued runs may share one traversal.

Scan-sharing is the engine's core trick (N analyzers fuse into one
pass), but it stopped at the run boundary: N tenants verifying the same
shared table still paid N full scans. The coalescer extends sharing
across runs — when compatible queued tickets target the same
``dataset_key``, the queue hands the worker a GROUP, the service runs
ONE superset scan, and each tenant's ``AnalyzerContext`` is sliced back
out (``AnalyzerContext.subset``; states are monoids, so a superset
scan's states project onto each suite's subset by construction).

This module is the pure POLICY half — no locks, no telemetry, no time
reads of its own (the queue passes its injected clock's ``now``):

- **compatibility** — same ``dataset_key`` and same config-derived
  plan-key surface (``engine.scan.coalesce_key_surface``, captured onto
  each ticket at submit). Incompatible runs simply don't coalesce.
- **priority** — INTERACTIVE never waits and never coalesces (its
  latency contract is the interactive reserve's whole point); STANDARD
  coalesces opportunistically (joins whatever is already queued, never
  waits for more); BATCH may additionally WAIT up to ``window_s`` after
  submit for peers to arrive, bounding the added latency by the window.
- **grouping atomicity** lives in ``RunQueue._take_group_locked`` —
  host selection and member absorption happen in one critical section,
  so concurrent idle workers can never each grab one member of a
  would-be group.

Every member keeps its own ``RunHandle``, submit-pinned deadline,
journal records, and telemetry run summary; a superset-scan failure
degrades to independent per-member execution in the service layer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from deequ_tpu.service.queue import Priority, RunTicket


@dataclass(frozen=True)
class CoalescePolicy:
    """Grouping rules evaluated by the queue under its own lock."""

    enabled: bool = False
    # how long a BATCH ticket may sit past submit waiting for peers
    # (0 = take immediately; only ever compared against the queue's
    # injected clock, never wall time)
    window_s: float = 0.0
    # ceiling on tickets per superset scan — bounds both the merged
    # plan's op count and the blast radius of one failed group
    max_members: int = 8

    def may_coalesce(self, ticket: RunTicket) -> bool:
        """INTERACTIVE runs neither host nor join a group: a superset
        scan's wall time is the max over members, and an interactive
        run must never inherit a batch suite's runtime. Row-level-sink
        runs never coalesce either — the egress artifact is per-run
        (one writer, one manifest), while a superset scan serves many
        tenants from one traversal. A PREEMPTED run resumes solo: its
        durable cursor is keyed to the plan token of the scan it was
        interrupted in, and joining a superset group would change that
        token — the cursor would not load and every conserved batch
        would be recomputed (docs/SERVICE.md "Preemption and
        autoscaling")."""
        if getattr(ticket.payload, "row_level_sink", None) is not None:
            return False
        if getattr(ticket, "preemptions", 0) > 0:
            return False
        return ticket.handle.priority > Priority.INTERACTIVE

    def compatible(
        self, host: RunTicket, candidate: RunTicket
    ) -> Optional[str]:
        """Why ``candidate`` must NOT join ``host``'s scan, or None.
        Surfaces are compared by equality — both unset (tickets pushed
        outside the service) is equal, matching the queue's trust in
        its producer."""
        if host.dataset_key is None or candidate.dataset_key is None:
            return "no dataset key"
        if host.dataset_key != candidate.dataset_key:
            return (
                f"dataset_key {host.dataset_key!r} != "
                f"{candidate.dataset_key!r}"
            )
        if host.coalesce_surface != candidate.coalesce_surface:
            return "config plan-key surface differs"
        return None

    def should_wait(
        self, ticket: RunTicket, now: float, compatible_peers: int
    ) -> bool:
        """True when ``ticket`` should stay queued a little longer to
        let more peers arrive: BATCH class, window still open, and the
        group it could form is not already at ``max_members``. STANDARD
        and INTERACTIVE never wait — they coalesce only with whatever
        is already there when a worker frees up."""
        if not self.enabled or self.window_s <= 0:
            return False
        if ticket.handle.priority < Priority.BATCH:
            return False
        if compatible_peers + 1 >= max(1, self.max_members):
            return False
        return (now - ticket.submitted_at) < self.window_s
