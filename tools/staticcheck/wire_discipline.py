"""Wire-discipline analyzer: the data layer stays on the host, and
wire dtype decisions stay out of per-batch loops.

The wire diet (docs/PERF.md) only works if layering holds:

``wire-discipline`` — checks over the wire path (ingest AND egress):

1. Modules under ``deequ_tpu/data/`` may not call ``jax.device_put``
   or ``jax.jit`` (or ``jax.pmap``). Device placement belongs to the
   engine — a data-layer put bypasses the wire pack (masks at 1
   bit/row, per-column codecs, transfer accounting) and ships fat
   unencoded buffers. The handful of deliberate resident-path helpers
   in ``data/table.py`` (device-built row masks, the fused mask
   unpack, the chunk-cache put that IS the resident wire) carry
   reasoned waivers.

2. In wire-path modules (``deequ_tpu/data/table.py``,
   ``deequ_tpu/data/parquet.py``, ``deequ_tpu/engine/scan.py``,
   ``deequ_tpu/engine/wire.py``), the wire-narrowing helpers
   (``narrow_int64_values``, ``narrow_codes``,
   ``narrowest_int_dtype``) must not be called lexically inside a
   ``for``/``while`` loop. A per-batch narrowing decision makes
   streamed batch dtypes depend on batch CONTENT, which breaks the
   fixed-layout no-recompile contract (``narrow_int64_values``
   docstring): one cold batch widens the wire and retraces the fused
   scan. Narrowing is decided once per run — from parquet statistics,
   a first-batch probe, or the whole materialized column.

3. The egress writer (``deequ_tpu/egress/``, every module except
   ``plan.py`` — the declared device half) is HOST-ONLY, the mirror
   image of rule 1: row-level bit planes arrive through the scan's
   packed epilogue, and a device call in the writer would open a
   second unaccounted device channel on the way OUT.

4. Egress scan-phase consumption must flush per fold: inside a
   ``consume*`` function in an egress module, a ``.append(...)`` /
   ``.extend(...)`` hoards host memory unless the same function also
   writes through (``.write`` / ``.flush`` / ``.write_table`` or an
   ``_emit*`` helper). The writer's host footprint is bounded by ONE
   span — never the table (docs/EGRESS.md "Memory discipline").
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Sequence, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

DATA_PREFIX = "deequ_tpu/data/"
#: jax entry points that place or compile for a device
DEVICE_CALLS = frozenset({"jax.device_put", "jax.jit", "jax.pmap"})
WIRE_PATH_FILES = (
    "deequ_tpu/data/table.py",
    "deequ_tpu/data/parquet.py",
    "deequ_tpu/engine/scan.py",
    "deequ_tpu/engine/wire.py",
)
#: dtype-deciding helpers; calling one per batch breaks the
#: fixed-layout contract
NARROWING_TAILS = frozenset(
    {"narrow_int64_values", "narrow_codes", "narrowest_int_dtype"}
)
EGRESS_PREFIX = "deequ_tpu/egress/"
#: the one egress module ALLOWED to touch jax: it builds the on-device
#: bit-pack planes that ride the fused scan (docs/EGRESS.md)
EGRESS_DEVICE_HALF = "deequ_tpu/egress/plan.py"
#: calls that accumulate host memory inside a consume path
BUFFERING_TAILS = frozenset({"append", "extend"})
#: calls that prove the consume path writes through per fold
FLUSH_TAILS = frozenset({"write", "flush", "write_table"})


class _WireScanner(ast.NodeVisitor):
    """One pass over a module: device-placement calls, and narrowing
    calls tagged with the lexical loop depth at the call site."""

    def __init__(self) -> None:
        self.loop_depth = 0
        self.device_calls: List[Tuple[str, int]] = []
        self.looped_narrowing: List[Tuple[str, int]] = []
        #: buffering calls inside ``consume*`` functions that never
        #: lexically write through: (function name, callee, line)
        self.hoarding: List[Tuple[str, str, int]] = []

    def _visit_loop(self, node: ast.AST) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    # a nested def inside a loop body runs per iteration only if called
    # there; but in this codebase closures defined in loops are rare
    # and a narrowing call inside one is exactly as per-batch as an
    # inline call, so the loop depth deliberately carries through.

    def visit_Call(self, node: ast.Call) -> None:
        callee = dotted_name(node.func)
        if callee:
            if callee in DEVICE_CALLS or callee.endswith(".device_put"):
                self.device_calls.append((callee, node.lineno))
            tail = callee.split(".")[-1]
            if tail in NARROWING_TAILS and self.loop_depth > 0:
                self.looped_narrowing.append((tail, node.lineno))
        self.generic_visit(node)

    def _visit_consume(self, node: ast.AST) -> None:
        """A ``consume*`` function is the scan's per-fold host sink;
        flag buffering calls unless the SAME function lexically writes
        through (``.flush``/``.write``/``.write_table`` or an
        ``_emit*`` helper — the writer's emit path is the flush)."""
        name = getattr(node, "name", "")
        if not name.startswith("consume"):
            self.generic_visit(node)
            return
        buffered: List[Tuple[str, int]] = []
        flushes = False
        for inner in ast.walk(node):
            if not isinstance(inner, ast.Call):
                continue
            callee = dotted_name(inner.func)
            if not callee:
                continue
            tail = callee.split(".")[-1]
            if tail in BUFFERING_TAILS and "." in callee:
                buffered.append((callee, inner.lineno))
            if tail in FLUSH_TAILS or tail.startswith("_emit"):
                flushes = True
        if not flushes:
            self.hoarding.extend(
                (name, callee, line) for callee, line in buffered
            )
        self.generic_visit(node)

    visit_FunctionDef = _visit_consume
    visit_AsyncFunctionDef = _visit_consume


class WireDisciplineAnalyzer(Analyzer):
    name = "wire"
    rules = ("wire-discipline",)
    description = (
        "device placement calls in the host-only data layer or egress "
        "writer; per-batch wire-narrowing decisions in loops; "
        "unflushed host buffering in egress consume paths"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            in_data = sf.rel.startswith(DATA_PREFIX)
            in_wire_path = sf.rel in WIRE_PATH_FILES
            in_egress = sf.rel.startswith(EGRESS_PREFIX)
            host_only_egress = (
                in_egress and sf.rel != EGRESS_DEVICE_HALF
            )
            if not (in_data or in_wire_path or in_egress):
                continue
            if sf.tree is None:
                continue
            scanner = _WireScanner()
            scanner.visit(sf.tree)
            if in_data:
                for callee, line in scanner.device_calls:
                    yield Finding(
                        rule="wire-discipline",
                        path=sf.rel,
                        line=line,
                        message=(
                            f"'{callee}' in the host-only data layer: "
                            "device placement belongs to the engine's "
                            "wire (pack -> put -> fused unpack); a "
                            "data-layer put ships unencoded buffers "
                            "and bypasses transfer accounting"
                        ),
                        symbol=callee,
                    )
            if host_only_egress:
                for callee, line in scanner.device_calls:
                    yield Finding(
                        rule="wire-discipline",
                        path=sf.rel,
                        line=line,
                        message=(
                            f"'{callee}' in the host-only egress "
                            "writer: device evaluation belongs to the "
                            "scan's plane functions (egress/plan.py); "
                            "bit planes arrive through the packed "
                            "epilogue — a writer-side device call "
                            "opens a second unaccounted device channel"
                        ),
                        symbol=callee,
                    )
            if in_egress:
                for fn, callee, line in scanner.hoarding:
                    yield Finding(
                        rule="wire-discipline",
                        path=sf.rel,
                        line=line,
                        message=(
                            f"'{callee}' buffers host memory inside "
                            f"'{fn}' without a lexical write-through "
                            "(.write/.flush/.write_table/_emit*): the "
                            "egress consume path must flush per scan "
                            "fold — its host footprint is bounded by "
                            "one span, never the table "
                            "(docs/EGRESS.md)"
                        ),
                        symbol=fn,
                    )
            if in_wire_path:
                for tail, line in scanner.looped_narrowing:
                    yield Finding(
                        rule="wire-discipline",
                        path=sf.rel,
                        line=line,
                        message=(
                            f"'{tail}' called inside a loop: a "
                            "per-batch narrowing decision makes "
                            "streamed dtypes content-dependent and "
                            "retraces the fused scan (fixed-layout "
                            "contract, narrow_int64_values docstring); "
                            "decide the wire dtype once per run"
                        ),
                        symbol=tail,
                    )


register(WireDisciplineAnalyzer())
