"""Ordered parallel host ingest: the decode/encode worker pool.

r9 made the streamed path bytes-bound on *encoded* bytes; this module
makes it CPU-parallel on the host side. The single prefetch worker
(engine/scan._prefetched) serializes Arrow decode, host pack and
wire-codec encode on one thread — on a multi-core host the wire diet
cannot cash out into rows/s. :func:`ordered_ingest` replaces it with a
bounded ordered pool:

- a READER thread walks the order-defining source iterator (cheap:
  Arrow-level slicing; parquet decompression is already parallel
  inside the pyarrow scanner) and enqueues ``(seq, item)`` work onto a
  bounded queue;
- N WORKER threads independently run the heavy ``work(item)`` stage
  (numpy conversion, validity/bit packing, wire-codec encode — all
  GIL-releasing);
- the CONSUMER (the generator returned to the scan loop) releases
  results strictly in sequence order, running the optional ``commit``
  stage — the ordered side of the contract (dictionary-delta absorb +
  cut, stale-wire re-pack) — on the scan thread at release time.

Ordering contract: at most ``lookahead`` items are in flight (queued +
working + done-awaiting-release), so host memory stays bounded; errors
raised anywhere (reader, worker, commit) surface on the consumer
thread at EXACTLY their sequence position, after every earlier item
has been yielded — which is what lets ``resilient_batches`` keep
computing the failing index as ``start + items_yielded``. Teardown
stops the reader and workers, releases the armed source-interrupt
event (a reader blocked inside a hung read wakes and exits), drains
the queues, and joins every thread: ``active_ingest_threads()`` (and
therefore ``scan.active_prefetch_workers``) drains to ``[]``.

Supervision: the consumer polls with ``supervisor.poll_s()`` and runs
``on_wait`` on every empty poll / ``note_arrival`` per release — the
same protocol as the single-worker path, so cancel/deadline/stall and
the watchdog attach to the pool unchanged.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

# Every thread this module (or scan._prefetched) starts registers here;
# tests assert the union is [] after teardown — the leak probe.
_INGEST_THREADS: "weakref.WeakSet" = weakref.WeakSet()


def register_ingest_thread(thread: threading.Thread) -> threading.Thread:
    """Register a host-ingest thread with the leak probe (the
    thread-discipline staticcheck rule requires every Thread in
    deequ_tpu to register here or carry a waiver)."""
    _INGEST_THREADS.add(thread)
    return thread


def active_ingest_threads():
    """Ingest threads (reader + workers + single-path prefetchers)
    still alive — the teardown-joins-everything probe for tests."""
    return [t for t in _INGEST_THREADS if t.is_alive()]


@dataclass
class IngestPoolStats:
    """Per-pool accounting, filled by the pool and (optionally) by the
    caller's work/commit closures; flushed as ONE ``ingest_pool``
    telemetry event on the consumer thread at teardown, so the
    per-stage busy fractions are diagnosable from the JSONL alone
    (tools/obs_report.py "ingest pool" line)."""

    workers: int = 0
    released: int = 0
    decode_s: float = 0.0  # worker-side heavy stage (Arrow -> numpy)
    encode_s: float = 0.0  # worker-side pack + wire-codec encode
    commit_s: float = 0.0  # consumer-side ordered stage
    idle_s: float = 0.0  # workers waiting for work
    stall_s: float = 0.0  # consumer waiting on the reassembly head
    wall_s: float = 0.0
    peak_in_flight: int = 0
    peak_in_flight_bytes: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            setattr(self, stage, getattr(self, stage) + seconds)

    def to_event_fields(self) -> Dict[str, Any]:
        return {
            "workers": int(self.workers),
            "released": int(self.released),
            "decode_s": round(self.decode_s, 6),
            "encode_s": round(self.encode_s, 6),
            "commit_s": round(self.commit_s, 6),
            "idle_s": round(self.idle_s, 6),
            "stall_s": round(self.stall_s, 6),
            "wall_s": round(self.wall_s, 6),
            "peak_in_flight": int(self.peak_in_flight),
            "peak_in_flight_bytes": int(self.peak_in_flight_bytes),
        }


def resolve_ingest_workers(configured: int) -> int:
    """``config.ingest_workers`` -> an actual worker count: 0 = auto
    (min(4, cpu)); never below 1."""
    if configured and configured > 0:
        return int(configured)
    import os

    return max(1, min(4, os.cpu_count() or 1))


def resolve_ingest_lookahead(configured: int, workers: int) -> int:
    """``config.ingest_lookahead`` -> in-flight bound: 0 = auto
    (2 * workers); never below workers (a tighter bound would idle
    workers by construction)."""
    if configured and configured > 0:
        return max(int(configured), workers)
    return 2 * workers


def process_sharded_feed(dataset, batch_size: int):
    """Prepare a dataset for the process-sharded global-array feed
    (``jax.make_array_from_process_local_data``): each process reads
    only its own row-group shard and contributes ``batch_size /
    process_count`` local rows per global batch.

    Returns ``(dataset, local_rows)``. Single-process (or a dataset
    without a ``shard_view`` planner) is the identity — the feed is
    still routed through ``make_array_from_process_local_data``, which
    with one process is semantically ``device_put(v, sharding)``; the
    multi-process leg swaps in the shard view and exchanges batch
    counts up front so every process runs the SAME number of
    collective puts (a short host pads with empty all-masked batches —
    the r5 uniform-exchange discipline: divergence raises everywhere
    instead of hanging the fleet in a collective).
    """
    import jax

    pc = jax.process_count()
    if pc <= 1 or not hasattr(dataset, "shard_view"):
        return dataset, int(batch_size)
    if batch_size % pc:
        raise ValueError(
            f"process-sharded ingest needs batch_size divisible by "
            f"process_count ({batch_size} % {pc} != 0)"
        )
    local_rows = batch_size // pc
    local = dataset.shard_view(jax.process_index(), pc)

    from jax.experimental import multihost_utils

    import numpy as np

    # the uniform exchange: every process learns every shard's batch
    # count BEFORE the first collective put, so imbalance pads instead
    # of hanging, and a zero-row shard fails loudly on EVERY host
    n_local = int(local.num_rows)
    # lint-ok: sync-discipline: host-side numpy over the allgather
    # payload — row counts, not device buffers; no readback happens
    counts = np.asarray(
        multihost_utils.process_allgather(
            # lint-ok: sync-discipline: builds the host payload
            np.asarray([n_local], dtype=np.int64)
        )
    ).reshape(-1)
    total_batches = int(
        max((int(c) + local_rows - 1) // local_rows for c in counts)
    )
    if total_batches > 0 and int(counts.min()) == 0:
        raise ValueError(
            "process-sharded ingest: a process was assigned zero rows "
            f"(shard row counts {counts.tolist()}) — shard planner "
            "cannot seed that host's batch structure; use fewer "
            "processes or a larger source"
        )
    return (
        _PaddedLocalFeed(local, local_rows, total_batches, counts),
        local_rows,
    )


class _PaddedLocalFeed:
    """Multi-process feed adapter: translates the engine's GLOBAL
    batch width to this process's local width and pads the tail so
    every process yields exactly ``total_batches`` batches (trailing
    pads are all-masked copies of the last real batch's structure).
    ``num_rows`` reports the GLOBAL total so engine row accounting
    stays cluster-wide. Does NOT declare ``supports_parallel_ingest``:
    the ordered pool re-engages per-host in a later revision."""

    def __init__(self, local, local_rows, total_batches, counts):
        self._local = local
        self._local_rows = int(local_rows)
        self._total_batches = int(total_batches)
        self._global_rows = int(sum(int(c) for c in counts))

    @property
    def num_rows(self) -> int:
        return self._global_rows

    def fingerprint(self):
        return self._local.fingerprint()

    def __getattr__(self, name):
        return getattr(self._local, name)

    def device_batches(self, requests, batch_size, start_batch=0):
        import numpy as np

        from deequ_tpu.data.table import ROW_MASK

        produced = start_batch
        template = None
        src = (
            self._local.device_batches(
                requests, self._local_rows, start_batch=start_batch
            )
            if start_batch
            else self._local.device_batches(requests, self._local_rows)
        )
        for batch in src:
            template = batch
            produced += 1
            yield batch
        while produced < self._total_batches:
            if template is None:
                raise ValueError(
                    "process-sharded ingest: cannot pad a shard that "
                    "yielded no batches"
                )
            from deequ_tpu.data.table import DICT_DELTA_PREFIX

            pad = {
                # lint-ok: sync-discipline: template batches are host
                # numpy (pre-put); zeroing them never touches a device
                k: np.zeros_like(np.asarray(v))
                for k, v in template.items()
                if not k.startswith(DICT_DELTA_PREFIX)
            }
            pad[ROW_MASK] = np.zeros(self._local_rows, dtype=bool)
            produced += 1
            yield pad


def ordered_ingest(
    items: Iterable[Any],
    work: Callable[[Any], Any],
    commit: Optional[Callable[[Any, Any], Any]] = None,
    *,
    workers: int,
    lookahead: int,
    supervisor=None,
    stats: Optional[IngestPoolStats] = None,
    sizer: Optional[Callable[[Any], int]] = None,
    emit_event: bool = True,
) -> Iterator[Any]:
    """Yield ``commit(work(item), item)`` for each item of ``items``,
    with ``work`` fanned out over ``workers`` threads and results
    released strictly in source order (see module docstring for the
    full ordering/teardown contract). ``sizer(result)`` (optional)
    prices a finished result in bytes for the peak-in-flight gauge."""
    workers = max(1, int(workers))
    lookahead = max(workers, int(lookahead))
    stats = stats or IngestPoolStats()
    stats.workers = workers
    started = time.monotonic()

    work_q: "queue.Queue" = queue.Queue(maxsize=lookahead)
    stop = threading.Event()
    cond = threading.Condition()
    # seq -> ("item", result, item, nbytes) | ("error", exc, None, 0) |
    # ("done", None, None, 0); guarded by cond
    results: Dict[int, Any] = {}
    state = {
        "next_seq": 0,  # next sequence number the reader will assign
        "released": 0,  # next sequence number the consumer will yield
        "in_flight_bytes": 0,
    }

    def put_work(msg) -> bool:
        # bounded put that notices an abandoned consumer — a plain
        # q.put would block forever holding batch buffers + the scanner
        while not stop.is_set():
            try:
                work_q.put(msg, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def deposit(seq: int, entry) -> None:
        with cond:
            results[seq] = entry
            if entry[0] == "item":
                state["in_flight_bytes"] += entry[3]
                stats.peak_in_flight_bytes = max(
                    stats.peak_in_flight_bytes, state["in_flight_bytes"]
                )
            cond.notify_all()

    def reader() -> None:
        seq = 0
        try:
            for item in items:
                # admission: at most ``lookahead`` items in flight —
                # bounds host memory (queued + decoding + awaiting
                # ordered release all count)
                with cond:
                    while (
                        seq - state["released"] >= lookahead
                        and not stop.is_set()
                    ):
                        cond.wait(timeout=0.1)
                    stats.peak_in_flight = max(
                        stats.peak_in_flight, seq - state["released"] + 1
                    )
                if stop.is_set():
                    return
                if not put_work((seq, item)):
                    return
                seq += 1
        # lint-ok: interrupt-swallow: the reader forwards the exception
        # (interrupts included) through the reassembly stage; the
        # consumer re-raises it on the scan thread at position seq
        except BaseException as exc:  # noqa: BLE001 — re-raised in order
            deposit(seq, ("error", exc, None, 0))
            return
        deposit(seq, ("done", None, None, 0))

    def worker_loop() -> None:
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                seq, item = work_q.get(timeout=0.1)
            except queue.Empty:
                stats.add("idle_s", time.monotonic() - t0)
                continue
            stats.add("idle_s", time.monotonic() - t0)
            try:
                result = work(item)
                nbytes = int(sizer(result)) if sizer is not None else 0
                deposit(seq, ("item", result, item, nbytes))
            # lint-ok: interrupt-swallow: a worker forwards its
            # exception (interrupts included) through the reassembly
            # stage; the consumer re-raises it on the scan thread at
            # EXACTLY position seq — after every earlier item
            except BaseException as exc:  # noqa: BLE001 — re-raised
                deposit(seq, ("error", exc, None, 0))

    reader_t = register_ingest_thread(
        threading.Thread(
            target=reader, daemon=True, name="deequ-tpu-ingest-reader"
        )
    )
    worker_ts = [
        register_ingest_thread(
            threading.Thread(
                target=worker_loop,
                daemon=True,
                name=f"deequ-tpu-ingest-{i}",
            )
        )
        for i in range(workers)
    ]
    reader_t.start()
    for t in worker_ts:
        t.start()

    def flush_stats() -> None:
        stats.wall_s = time.monotonic() - started
        if not emit_event:
            return
        from deequ_tpu.telemetry import get_telemetry

        # emitted on the CONSUMER (scan) thread: telemetry run
        # captures are thread-scoped
        get_telemetry().event("ingest_pool", **stats.to_event_fields())

    try:
        while True:
            want = state["released"]
            with cond:
                entry = results.get(want)
                if entry is None:
                    t0 = time.monotonic()
                    timeout = (
                        supervisor.poll_s()
                        if supervisor is not None
                        else 0.1
                    )
                    cond.wait(timeout=timeout)
                    stats.stall_s += time.monotonic() - t0
                    entry = results.get(want)
                if entry is not None:
                    del results[want]
            if entry is None:
                if supervisor is not None:
                    supervisor.on_wait()  # cancel/deadline/stall check
                continue
            tag, payload, item, nbytes = entry
            if tag == "error":
                raise payload
            if tag == "done":
                return
            if supervisor is not None:
                supervisor.note_arrival()
            t0 = time.monotonic()
            released = (
                commit(payload, item) if commit is not None else payload
            )
            stats.commit_s += time.monotonic() - t0
            stats.released += 1
            with cond:
                state["released"] = want + 1
                state["in_flight_bytes"] -= nbytes
                cond.notify_all()
            yield released
    finally:
        stop.set()  # consumer done or raised: release reader + workers
        if supervisor is not None:
            # a reader blocked inside a hung source read can't see
            # ``stop`` — set its armed interrupt event so it raises out
            supervisor.release_source()
        with cond:
            cond.notify_all()
        try:
            while True:
                work_q.get_nowait()
        except queue.Empty:
            pass
        reader_t.join(timeout=2.0)
        for t in worker_ts:
            t.join(timeout=2.0)
        flush_stats()
