from deequ_tpu.anomalydetection.base import (
    Anomaly,
    AnomalyDetectionStrategy,
    AnomalyDetector,
    DataPoint,
    DetectionResult,
)
from deequ_tpu.anomalydetection.seasonal import (
    HoltWinters,
    MetricInterval,
    SeriesSeasonality,
)
from deequ_tpu.anomalydetection.strategies import (
    AbsoluteChangeStrategy,
    BatchNormalStrategy,
    OnlineNormalStrategy,
    RelativeRateOfChangeStrategy,
    SimpleThresholdStrategy,
)
from deequ_tpu.anomalydetection.wiring import AnomalyCheckConfig

__all__ = [
    "AbsoluteChangeStrategy",
    "Anomaly",
    "AnomalyCheckConfig",
    "AnomalyDetectionStrategy",
    "AnomalyDetector",
    "BatchNormalStrategy",
    "DataPoint",
    "DetectionResult",
    "HoltWinters",
    "MetricInterval",
    "OnlineNormalStrategy",
    "RelativeRateOfChangeStrategy",
    "SeriesSeasonality",
    "SimpleThresholdStrategy",
]
