"""Deadlines, cooperative cancellation, and watchdog supervision.

PR 3 (engine/resilience.py) made the fused scan survive batches that
FAIL; this module makes it survive batches that HANG — and gives every
run a wall-clock budget, which the reference inherits from its
schedulers (deequ runs inside ingestion pipelines that kill stuck
stages; SURVEY.md production story). Pieces:

- :class:`RunBudget` — a wall deadline plus an optional per-batch
  stall limit, measured on an INJECTABLE clock
  (:class:`MonotonicClock` for production, :class:`ManualClock` for
  tests — no resilience test ever wall-sleeps; fake time is advanced
  by the fault that is actually hanging, so healthy real-time work can
  never trip a spurious stall).
- :class:`CancelToken` — thread-safe, composable (parent cancellation
  propagates to children; a child can cancel independently), carries a
  reason. External cancellation, SIGTERM mapping, and the profiler's
  shared multi-pass budget all ride the same token.
- :class:`ScanSupervisor` + :class:`Watchdog` — per-scan supervision.
  The scan loop notes progress per batch (which re-arms the stall
  timer); the streaming consumer polls its prefetch queue with a short
  timeout and checks the supervisor on every empty poll; the watchdog
  THREAD covers the stages that cannot poll (the resident chunk-staging
  generator blocked inside a hung read) by setting the armed interrupt
  event, which releases the blocked source so it raises
  :class:`~deequ_tpu.engine.resilience.ScanStalled` — a
  ``TransientScanError``, so a stall flows straight into PR 3's
  retry -> quarantine -> ``ScanDegradation`` path.
- :class:`ScanInterrupted` (``RunCancelled`` / ``DeadlineExceeded``) —
  derives from ``BaseException`` exactly like ``ScanKilled``: the
  retry/quarantine machinery catches ``Exception`` only, so an
  interrupt unwinds to the engine loop, which exits CLEANLY — persists
  a final checkpoint cursor (resume is bit-identical, the PR 3
  contract), records a :class:`ScanInterruption` on the engine, and
  returns partial states so the runner still computes partial metrics.
- :class:`AdmissionController` — a FIFO ticket queue bounding
  concurrent runs (``config.max_concurrent_runs``); queued runs wait
  under their own deadline instead of oversubscribing the device.
- :func:`install_graceful_shutdown` — opt-in SIGTERM handler that maps
  process shutdown onto the process-wide shutdown
  :class:`CancelToken`, so an orchestrator's TERM becomes a
  checkpointed, resumable exit instead of lost work.

See docs/RESILIENCE.md ("Deadlines & cancellation") for the state
machine and the user-facing API on ``AnalysisRunner`` /
``VerificationSuite``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence


# --------------------------------------------------------------------------
# Interrupt exceptions
# --------------------------------------------------------------------------


class ScanInterrupted(BaseException):
    """A cooperative interrupt (cancellation or deadline exhaustion).

    A ``BaseException`` ON PURPOSE, same pattern as
    :class:`~deequ_tpu.engine.resilience.ScanKilled`: the batch-level
    retry/quarantine machinery catches ``Exception`` only, so an
    interrupt tunnels through it to the engine's scan loop — which is
    the ONE place that handles it (final checkpoint, interruption
    record, clean partial-result exit). It never escapes a run."""

    kind = "interrupted"


class RunCancelled(ScanInterrupted):
    """External cancellation: a :class:`CancelToken` fired (user code,
    a parent token, or the SIGTERM shutdown token)."""

    kind = "cancelled"


class DeadlineExceeded(ScanInterrupted):
    """The run's :class:`RunBudget` wall deadline is exhausted (or an
    admission-queued run waited past it)."""

    kind = "deadline"


# --------------------------------------------------------------------------
# Clocks (injectable — tests never wall-sleep)
# --------------------------------------------------------------------------


class MonotonicClock:
    """Production clock: ``time.monotonic``."""

    def now(self) -> float:
        return time.monotonic()

    def queue_poll_s(self, stall_s: Optional[float] = None) -> float:
        """Real-time poll interval for blocking waits supervised on this
        clock — short enough to detect a stall promptly, long enough
        not to burn CPU."""
        if stall_s:
            return max(min(stall_s / 4.0, 0.5), 0.01)
        return 0.25


class ManualClock:
    """Deterministic test clock: ``now()`` only moves via ``advance``.

    Fake time is advanced by whatever is ACTUALLY consuming it — a
    ``hang_at_batch`` fault ticks the clock while it blocks, a
    ``slow_batch`` fault advances it by the configured delay — never by
    a free-running timer, so healthy batches that take real wall time
    (a jit compile, a slow CI host) can NEVER trip a spurious stall.
    ``queue_poll_s`` is a tiny REAL timeout so supervised waits re-check
    fake time thousands of times per real second."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def advance(self, seconds: float) -> float:
        with self._lock:
            self._now += float(seconds)
            return self._now

    def queue_poll_s(self, stall_s: Optional[float] = None) -> float:
        return 0.002


# --------------------------------------------------------------------------
# Cancellation
# --------------------------------------------------------------------------


class CancelToken:
    """Thread-safe cancellation flag with a reason and parent/child
    composition: cancelling a parent cancels every child (transitively);
    a child cancels independently without touching its parent. Linking
    to an already-cancelled parent cancels the child immediately."""

    def __init__(self, parent: Optional["CancelToken"] = None):
        self._event = threading.Event()
        self._reason: Optional[str] = None
        self._lock = threading.Lock()
        self._children: List["CancelToken"] = []
        if parent is not None:
            parent._link(self)

    def _link(self, child: "CancelToken") -> None:
        with self._lock:
            if not self._event.is_set():
                self._children.append(child)
                return
            reason = self._reason
        child.cancel(reason or "cancelled")

    def child(self) -> "CancelToken":
        return CancelToken(parent=self)

    def cancel(self, reason: str = "cancelled") -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._reason = reason
            self._event.set()
            children = list(self._children)
            self._children = []  # delivered; drop the references
        for c in children:
            c.cancel(reason)

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> Optional[str]:
        # lint-ok: lock-discipline: _reason is written exactly once,
        # before _event.set(); readers that gate on the event see it
        return self._reason

    def raise_if_cancelled(self) -> None:
        if self._event.is_set():
            # lint-ok: lock-discipline: read after _event.is_set() —
            # Event.set() publishes the preceding _reason write
            raise RunCancelled(self._reason or "cancelled")

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def __repr__(self) -> str:
        state = (
            # lint-ok: lock-discipline: debug snapshot; may lag a
            # concurrent cancel by design
            f"cancelled: {self._reason!r}" if self.cancelled else "active"
        )
        return f"CancelToken({state})"


# --------------------------------------------------------------------------
# Run budget
# --------------------------------------------------------------------------


@dataclass
class RunBudget:
    """A run's time envelope: optional wall ``deadline_s`` and optional
    per-batch ``stall_s`` limit, both measured on ``clock``. ``start()``
    pins the epoch LAZILY on first use and is idempotent, so one budget
    shared across a multi-scan run (the profiler's three passes, the
    runner's deferred fallbacks) burns a single envelope rather than
    restarting per scan."""

    deadline_s: Optional[float] = None
    stall_s: Optional[float] = None
    clock: Any = field(default_factory=MonotonicClock)
    _started_at: Optional[float] = field(
        default=None, init=False, repr=False, compare=False
    )

    def start(self) -> "RunBudget":
        if self._started_at is None:
            self._started_at = self.clock.now()
        return self

    def elapsed(self) -> float:
        if self._started_at is None:
            return 0.0
        return self.clock.now() - self._started_at

    def remaining(self) -> Optional[float]:
        """Seconds left until the deadline (None = no deadline).
        Negative once exhausted."""
        if self.deadline_s is None:
            return None
        return self.deadline_s - self.elapsed()

    def expired(self) -> bool:
        remaining = self.remaining()
        return remaining is not None and remaining < 0

    def check(self) -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"run deadline of {self.deadline_s}s exhausted "
                f"(elapsed {self.elapsed():.3f}s)"
            )


# --------------------------------------------------------------------------
# Interruption record (rides AnalyzerContext / VerificationResult)
# --------------------------------------------------------------------------


@dataclass
class ScanInterruption:
    """Provenance for a run that exited early: why, how far it got, and
    whether a resumable checkpoint cursor was persisted. Metrics on an
    interrupted run cover batches ``[0, batch_index)`` — correct over
    the rows scanned; ``config.degradation_policy`` decides what that
    does to a VerificationSuite status (same floor as quarantine)."""

    kind: str  # "cancelled" | "deadline"
    reason: str
    batch_index: int = 0
    row_offset: int = 0
    checkpointed: bool = False

    @staticmethod
    def merge_optional(
        a: Optional["ScanInterruption"], b: Optional["ScanInterruption"]
    ) -> Optional["ScanInterruption"]:
        # the FIRST interrupt is the one that stopped the run; later
        # scans in the same run short-circuit against it
        return a if a is not None else b

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "reason": self.reason,
            "batch_index": self.batch_index,
            "row_offset": self.row_offset,
            "checkpointed": self.checkpointed,
        }


# --------------------------------------------------------------------------
# Supervision
# --------------------------------------------------------------------------


class ScanSupervisor:
    """Per-scan supervision state shared by the scan loop, the
    streaming prefetch consumer, and the watchdog thread.

    Progress model: ``note_arrival()`` (called inside the batch
    iterator as each item lands) re-arms the stall timer — "armed per
    batch". Detection is ONE rule, elapsed-since-last-arrival >
    ``stall_s``, checked from three places so whichever stage is
    actually blocked reports it: on item arrival (a slow batch), on an
    empty prefetch poll (a hung streaming worker), and from the
    watchdog thread (a hung stage that cannot poll — the resident
    staging generator). The watchdog cannot raise into the blocked
    thread, so it INTERRUPTS instead: it sets the armed interrupt event
    (handed to the source via ``dataset.attach_interrupt``), and the
    released source raises ``ScanStalled`` from the blocked call."""

    def __init__(
        self,
        budget: Optional[RunBudget] = None,
        tokens: Sequence[Optional[CancelToken]] = (),
    ):
        self.budget = budget.start() if budget is not None else None
        self.tokens: List[CancelToken] = [t for t in tokens if t is not None]
        self.clock = budget.clock if budget is not None else MonotonicClock()
        self._lock = threading.Lock()
        self._last_progress = self.clock.now()
        self._stall_counted = False
        self._interrupt_event: Optional[threading.Event] = None
        self._watchdog: Optional["Watchdog"] = None
        self.stalls = 0
        self._stall_events: List[Dict[str, Any]] = []

    # -- configuration views -------------------------------------------

    @property
    def stall_s(self) -> Optional[float]:
        return self.budget.stall_s if self.budget is not None else None

    def poll_s(self) -> float:
        return self.clock.queue_poll_s(self.stall_s)

    # -- interrupt checks (consumer side) ------------------------------

    def check(self) -> None:
        """Raise the pending interrupt, if any (cancel before deadline:
        an explicit cancel is the more specific reason)."""
        for token in self.tokens:
            token.raise_if_cancelled()
        if self.budget is not None:
            self.budget.check()

    def interrupted(self) -> bool:
        return any(t.cancelled for t in self.tokens) or (
            self.budget is not None and self.budget.expired()
        )

    def _stalled(self) -> bool:
        stall = self.stall_s
        if not stall:
            return False
        with self._lock:
            last = self._last_progress
        return self.clock.now() - last > stall

    def on_wait(self) -> None:
        """Called by the streaming consumer on every EMPTY prefetch
        poll: the one moment it is provably blocked on the source."""
        self.check()
        if self._stalled():
            self._record_stall()
            self.reset_progress()  # the retry must not re-trip instantly
            from deequ_tpu.engine.resilience import ScanStalled

            raise ScanStalled(
                f"no batch for more than {self.stall_s}s "
                "(prefetch queue empty) — stalled source"
            )

    def note_arrival(self) -> None:
        """Called inside the batch iterator as each item lands. A batch
        that took longer than ``stall_s`` end to end is itself a stall
        (this is what catches a slow batch the consumer never had to
        poll for); a timely batch re-arms the timer."""
        if self._stalled():
            self._record_stall()
            self.reset_progress()
            from deequ_tpu.engine.resilience import ScanStalled

            raise ScanStalled(
                f"batch exceeded the {self.stall_s}s stall limit"
            )
        self.reset_progress()

    def reset_progress(self) -> None:
        """Re-arm the stall timer (each batch arrival; each iterator
        (re)start — a retried iterator must start with a fresh window)."""
        with self._lock:
            self._last_progress = self.clock.now()
            self._stall_counted = False

    # -- blocked-source interruption -----------------------------------

    def arm_source(self) -> threading.Event:
        """A FRESH interrupt event for the next source iterator (fresh
        per restart: a consumed event from the previous stall must not
        pre-release the retry)."""
        event = threading.Event()
        with self._lock:
            self._interrupt_event = event
        return event

    def release_source(self) -> None:
        """Unblock whatever holds the armed interrupt event (watchdog
        on stall/cancel/deadline; consumer teardown on exit) — the
        hung-prefetch-worker release valve."""
        with self._lock:
            event = self._interrupt_event
        if event is not None:
            event.set()

    def _record_stall(self) -> None:
        with self._lock:
            if self._stall_counted:
                return  # watchdog + consumer race: count once per arm
            self._stall_counted = True
            self.stalls += 1
            # the EVENT is deferred: this may run on the watchdog
            # thread, and telemetry run captures are thread-scoped —
            # the engine flushes events on the scan thread at scan end
            self._stall_events.append(
                {"stall_s": self.stall_s, "stalls": self.stalls}
            )
        from deequ_tpu.telemetry import get_telemetry

        get_telemetry().counter("engine.stalls_detected").inc()

    def flush_stall_events(self) -> None:
        """Emit deferred ``scan_stalled`` events on the CALLING thread
        (the engine's scan thread, inside any live run capture)."""
        with self._lock:
            pending, self._stall_events = self._stall_events, []
        if not pending:
            return
        from deequ_tpu.telemetry import get_telemetry

        tm = get_telemetry()
        for fields in pending:
            tm.event("scan_stalled", **fields)

    def watchdog_check(self) -> None:
        """One watchdog tick: on stall, cancellation, or deadline,
        interrupt the blocked source. The consumer-side checks then
        classify — stall retries/quarantines, cancel/deadline exit."""
        interrupt = self.interrupted()
        if self._stalled():
            self._record_stall()
            interrupt = True
        if interrupt:
            self.release_source()

    # -- watchdog lifecycle --------------------------------------------

    def start_watchdog(self) -> None:
        if self._watchdog is None:
            self._watchdog = Watchdog(self)
            self._watchdog.start()

    def stop_watchdog(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None


class Watchdog:
    """Background thread driving :meth:`ScanSupervisor.watchdog_check`
    at the supervisor's poll interval. Daemon + joined-with-timeout on
    stop, so a scan can never leak it."""

    def __init__(self, supervisor: ScanSupervisor):
        self._supervisor = supervisor
        self._stop = threading.Event()
        # lint-ok: thread-discipline: watchdog has its own lifecycle —
        # joined-with-timeout in Watchdog.stop(), not an ingest worker
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="deequ-tpu-watchdog"
        )

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._supervisor.poll_s()):
            try:
                self._supervisor.watchdog_check()
            except Exception:  # noqa: BLE001 — the watchdog must not die
                pass

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


# --------------------------------------------------------------------------
# Admission control
# --------------------------------------------------------------------------


class AdmissionController:
    """FIFO bounded admission for analysis runs: at most ``limit`` run
    concurrently, the rest queue IN ORDER (a plain semaphore wakes
    waiters arbitrarily — ticket order makes queueing fair and
    testable). Waiters poll in short real intervals so a queued run's
    own :class:`RunBudget` (possibly on a fake clock) and cancel token
    stay live while it waits.

    High-watermark gate (docs/RESILIENCE.md "Memory pressure"): with
    ``watermark_bytes`` set, a run also queues while admitting its
    ``estimated_bytes`` (engine.estimated_run_bytes, from the scan's
    row-capacity geometry) would push the byte sum of ACTIVE runs past
    the watermark — concurrent runs queue instead of co-OOMing. A
    single run larger than the whole watermark still admits when
    nothing else is active (it must run eventually; backoff is its
    safety net)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._active = 0
        self._active_bytes = 0
        self._queue: "deque[int]" = deque()
        self._next_ticket = 0

    def _admissible_locked(
        self, limit: int, estimated_bytes: int, watermark_bytes: int
    ) -> bool:
        if limit > 0 and self._active >= limit:
            return False
        if (
            watermark_bytes > 0
            and estimated_bytes > 0
            and self._active > 0
            and self._active_bytes + estimated_bytes > watermark_bytes
        ):
            return False
        return True

    def acquire(
        self,
        limit: int,
        budget: Optional[RunBudget] = None,
        tokens: Sequence[Optional[CancelToken]] = (),
        estimated_bytes: int = 0,
        watermark_bytes: int = 0,
    ) -> None:
        """Block until admitted. ``limit <= 0`` means no concurrency
        bound (the watermark alone gates). Raises
        :class:`DeadlineExceeded` / :class:`RunCancelled` if the run's
        envelope closes while it is still queued — a run that cannot
        start in time must not start."""
        from deequ_tpu.telemetry import get_telemetry

        live = [t for t in tokens if t is not None]
        if budget is not None:
            budget.start()  # the envelope opens at submission: time
            # spent queued counts against the deadline (idempotent —
            # the scan supervisor re-starting it later is a no-op)
        with self._cond:
            if not self._queue and self._admissible_locked(
                limit, estimated_bytes, watermark_bytes
            ):
                self._active += 1
                self._active_bytes += max(0, int(estimated_bytes))
                return
            ticket = self._next_ticket
            self._next_ticket += 1
            self._queue.append(ticket)
            get_telemetry().counter("engine.runs_queued").inc()
            try:
                while not (
                    self._queue[0] == ticket
                    and self._admissible_locked(
                        limit, estimated_bytes, watermark_bytes
                    )
                ):
                    for token in live:
                        token.raise_if_cancelled()
                    if budget is not None and budget.expired():
                        raise DeadlineExceeded(
                            "queued for admission past the run deadline "
                            f"({budget.deadline_s}s)"
                        )
                    self._cond.wait(timeout=0.02)
                self._queue.popleft()
                self._active += 1
                self._active_bytes += max(0, int(estimated_bytes))
            except BaseException:
                if ticket in self._queue:
                    self._queue.remove(ticket)
                self._cond.notify_all()
                raise

    def release(self, estimated_bytes: int = 0) -> None:
        with self._cond:
            self._active -= 1
            self._active_bytes = max(
                0, self._active_bytes - max(0, int(estimated_bytes))
            )
            self._cond.notify_all()

    def snapshot(self) -> Dict[str, int]:
        with self._cond:
            return {
                "active": self._active,
                "queued": len(self._queue),
                "active_bytes": self._active_bytes,
            }


_ADMISSION = AdmissionController()


def admission_controller() -> AdmissionController:
    """The process-wide admission controller
    (``config.max_concurrent_runs`` bounds it; 0 disables)."""
    return _ADMISSION


# --------------------------------------------------------------------------
# Graceful shutdown (SIGTERM -> process-wide cancellation)
# --------------------------------------------------------------------------


_shutdown_lock = threading.Lock()
_shutdown_token = CancelToken()
_shutdown_installed = False


def shutdown_token() -> CancelToken:
    """The process-wide shutdown token. Engine supervisors watch it
    once a graceful-shutdown handler is installed."""
    return _shutdown_token


def shutdown_installed() -> bool:
    return _shutdown_installed


def reset_shutdown_token() -> CancelToken:
    """Replace the shutdown token with a fresh one (tests; or a worker
    that survived a drain request and wants to serve again)."""
    global _shutdown_token
    with _shutdown_lock:
        _shutdown_token = CancelToken()
        return _shutdown_token


def install_graceful_shutdown(
    signals: Sequence[int] = None,
) -> Callable[[], None]:
    """Opt-in: map SIGTERM (by default) onto the process-wide shutdown
    token, so an orchestrator's TERM lands mid-scan as a cooperative
    cancel — final checkpoint persisted, partial metrics returned,
    prefetch worker joined — instead of lost work. Returns an
    ``uninstall()`` callable restoring the previous handlers. Must be
    called from the main thread (CPython signal rule)."""
    import signal as _signal

    global _shutdown_installed
    if signals is None:
        signals = (_signal.SIGTERM,)

    def _handler(signum, frame):  # noqa: ARG001 — signal signature
        shutdown_token().cancel(
            f"received signal {_signal.Signals(signum).name}"
        )

    previous = {}
    for sig in signals:
        previous[sig] = _signal.signal(sig, _handler)
    with _shutdown_lock:
        _shutdown_installed = True

    def uninstall() -> None:
        global _shutdown_installed
        for sig, old in previous.items():
            _signal.signal(sig, old)
        with _shutdown_lock:
            _shutdown_installed = False

    return uninstall
