"""CLI: ``python -m tools.staticcheck [root] [options]``.

Exit status 0 means zero unwaived findings (the tier-1 gate and CI
both key off this); 1 means at least one. ``--json`` emits the full
machine-readable artifact (summary + every finding, waived ones
included and marked) for tooling; ``--all`` shows waived findings in
the human listing too; ``--rules`` narrows to a comma-separated rule
subset; ``--list-rules`` prints the catalog.
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from tools.staticcheck import (
    all_rules,
    default_root,
    run_analyzers,
    summarize,
    to_json,
    unwaived,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.staticcheck",
        description="AST-based static analysis for the deequ_tpu tree",
    )
    parser.add_argument("root", nargs="?", default=None)
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument(
        "--rules", default=None, help="comma-separated rule subset"
    )
    parser.add_argument(
        "--all",
        action="store_true",
        help="also list waived findings in human output",
    )
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)
    if args.list_rules:
        for rule, description in all_rules():
            print(f"{rule}: {description}")
        return 0
    root = args.root or default_root()
    if not os.path.isdir(root):
        parser.error(f"root is not a directory: {root}")
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    findings = run_analyzers(root, rules=rules)
    if args.as_json:
        print(to_json(findings, root))
        return 1 if unwaived(findings) else 0
    shown = findings if args.all else unwaived(findings)
    for finding in shown:
        print(finding.render())
    stats = summarize(findings)
    print(
        f"staticcheck: {stats['unwaived']} finding(s), "
        f"{stats['waived']} waived"
    )
    return 1 if stats["unwaived"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
