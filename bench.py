"""Benchmark harness: measures the BASELINE.json configs on one chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The headline metric is rows/sec/chip for the full ColumnProfiler
(BASELINE.json: 1B rows x 50 cols TPC-DS in <60s on v5e-8 => a per-chip
baseline of 1e9 rows / 60 s / 8 chips ~= 2.083e6 rows/sec/chip).
The workload here is scaled to one chip's memory: the profiler runs once
to populate compile caches (a 1B-row run amortizes compilation across
~250 batches; a scaled run must not be charged full compile cost), then
the measured run profiles FRESH data of identical shape, so transfers
and device execution are fully re-measured.

Secondary configs (fused numeric bundle, grouping, sketches) are timed
the same way and reported in the detail dict on stderr.

The run is BUDGETED (--budget seconds, default
$DEEQU_TPU_BENCH_BUDGET_S or 600): secondary configs are skipped —
with a note in the detail dict — once the remaining budget can't cover
their estimated cost, and the headline JSON line is ALWAYS printed.
``--quick`` runs the headline config only, at reduced scale.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


NORTH_STAR_ROWS_PER_SEC_PER_CHIP = 1e9 / 60.0 / 8.0  # BASELINE.json


def _timed(fn):
    """(wall_s, bytes_shipped, link MB/s, result) for one run — the
    transfer counter lets a slow round be decomposed into link vs
    compute straight from the bench artifact (VERDICT r2 weak #6)."""
    from deequ_tpu.data.table import transfer_bytes

    b0 = transfer_bytes()
    t0 = time.time()
    result = fn()
    wall = time.time() - t0
    shipped = transfer_bytes() - b0
    return wall, shipped, (shipped / wall / 1e6 if wall > 0 else 0.0), result


def _phases(run_metadata):
    """Sum the engine's per-pass wall decomposition events into one
    dict (VERDICT r3 next #2): host_wait_s = source read/convert;
    put_s = transfer dispatch incl. link backpressure; dispatch_s =
    jitted step dispatch; first_step_s = the first step alone (carries
    any trace/compile cost, so cold runs don't read as dispatch
    overhead); sync_s = blocked on the device queue (remaining
    transfers + compute). wall ≈ sum of the five; under a saturated
    link, attribution BETWEEN buckets is indicative only (GIL/
    backpressure smear — see deequ_tpu.telemetry.phases.PhaseClock)."""
    from deequ_tpu.telemetry import summarize_phases

    return summarize_phases(
        run_metadata.events if run_metadata else []
    )


# --------------------------------------------------------------------------
# Crash-proof harness: host probe, row auto-sizing, subprocess-per-config
# --------------------------------------------------------------------------


def probe_host() -> dict:
    """What this host can actually sustain: cores, available memory and
    the jax backend — recorded in the artifact so a round's numbers are
    interpretable, and fed to :func:`autosize` (ROADMAP item 1: the
    1-core CI container segfaults ≥1M-row streamed runs that a real
    host shrugs off)."""
    probe = {"cpu_count": os.cpu_count() or 1, "mem_available_mb": None}
    try:
        with open("/proc/meminfo", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("MemAvailable:"):
                    probe["mem_available_mb"] = int(line.split()[1]) // 1024
                    break
    except OSError:
        pass
    try:
        import jax

        probe["jax_backend"] = jax.default_backend()
        probe["jax_device_count"] = jax.device_count()
    except Exception as exc:  # noqa: BLE001 — probe must never kill the bench
        probe["jax_error"] = repr(exc)
    return probe


def autosize(probe: dict) -> dict:
    """Row sizing for this host. ``$DEEQU_TPU_BENCH_SCALE`` overrides
    everything; otherwise small (≤2-core) hosts run at 1/4 scale — 1/8
    under real memory pressure — and streamed configs are additionally
    capped below the documented ≥1M-row crash threshold, so the bench
    measures the engine rather than the container's limits."""
    env = os.environ.get("DEEQU_TPU_BENCH_SCALE", "")
    cores = probe.get("cpu_count") or 1
    mem_mb = probe.get("mem_available_mb")
    if env:
        scale = max(0.001, float(env))
    else:
        scale = 0.25 if cores <= 2 else 1.0
        if mem_mb is not None and mem_mb < 6_000:
            scale = min(scale, 0.125 if mem_mb < 3_000 else 0.25)
    streaming_cap = 800_000 if (cores <= 2 and not env) else None
    return {"row_scale": scale, "streaming_row_cap": streaming_cap}


def _sized(base_rows: int, sizing: dict, streamed: bool = False) -> int:
    rows = max(100_000, int(base_rows * sizing["row_scale"]))
    cap = sizing.get("streaming_row_cap") if streamed else None
    return min(rows, cap) if cap else rows


#: config name -> thunk over the sized-args dict. Looked up CHILD-SIDE
#: by :func:`_bench_child`, so only ``(name, args)`` cross the spawn
#: pipe — the lambdas themselves are never pickled.
CONFIG_REGISTRY = {
    "profiler": lambda a: bench_profiler(a["rows"], a["cols"]),
    "profiler_50col": lambda a: bench_profiler_wide(a["rows"], 50),
    "profiler_50col_8m": lambda a: bench_profiler_wide(a["rows"], 50),
    "fused_bundle_10col": lambda a: bench_fused_bundle(a["rows"]),
    "grouping_5cat": lambda a: bench_grouping(a["rows"]),
    "one_pass_spill_grouping": lambda a: bench_one_pass_grouping(a["rows"]),
    "sketches_hll_kll": lambda a: bench_sketches(a["rows"]),
    "resilience_overhead": lambda a: bench_resilience_overhead(a["rows"]),
    "memory_backoff_overhead": (
        lambda a: bench_memory_backoff_overhead(a["rows"])
    ),
    "watchdog_overhead": lambda a: bench_watchdog_overhead(a["rows"]),
    "service_concurrent_suites": (
        lambda a: bench_service_concurrent_suites(a["rows"], a["clients"])
    ),
    "service_coalesced_suites": (
        lambda a: bench_service_coalesced_suites(a["rows"], a["clients"])
    ),
    "service_elastic_placement": (
        lambda a: bench_service_elastic_placement(a["rows"], a["clients"])
    ),
    "service_preemption": (
        lambda a: bench_service_preemption(a["rows"], a["clients"])
    ),
    "spill_grouping_12M_distinct": lambda a: bench_spill_grouping(a["rows"]),
    "joint_grouping_mi_1Mcard_pair": lambda a: bench_joint_grouping(a["rows"]),
    "streaming_parquet": (
        lambda a: bench_streaming_parquet(a["rows"], a["cols"])
    ),
    "streaming_wire_diet": lambda a: bench_streaming_wire_diet(a["rows"]),
    "streaming_ingest_parallel": (
        lambda a: bench_streaming_ingest_parallel(a["rows"], a["cols"])
    ),
    "streaming_bundle_100m": lambda a: bench_streaming_bundle_100m(a["rows"]),
    "rowlevel_egress": lambda a: bench_rowlevel_egress(a["rows"]),
    "egress_resume": lambda a: bench_egress_resume(a["rows"]),
    "fleet_failover": lambda a: bench_fleet_failover(a["rows"]),
}


#: extra environment a config's spawned child needs, applied by
#: ``run_one`` around the spawn and restored after (the parent's
#: already-initialized jax backend is unaffected — only the child's
#: fresh import reads it). ``service_elastic_placement`` measures
#: sub-slice placement, which needs a multi-device pool; on a CPU
#: host that means forcing virtual host devices.
CONFIG_CHILD_ENV = {
    "service_elastic_placement": {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    },
    # BENCH_r12 bisection (docs/PERF.md "Streaming crash family"): the
    # r11 SIGSEGV/SIGABRT pair did NOT reproduce on this host — both
    # configs run clean at 800k rows with a warm persistent XLA cache
    # present. The cache remains the one shared mutable input these two
    # children have that the healthy configs don't exercise as hard, so
    # it stays disabled here as a cheap containment (cost: one extra
    # in-child compile, ~2s) until a reproducing host pins the cause.
    "streaming_wire_diet": {"DEEQU_TPU_COMPILE_CACHE": ""},
    "streaming_ingest_parallel": {"DEEQU_TPU_COMPILE_CACHE": ""},
}


def _apply_child_env(name: str):
    """Set a config's CONFIG_CHILD_ENV vars, returning a restore
    thunk. XLA_FLAGS composes: an existing device-count flag wins
    (the caller already chose a pool size), anything else is appended
    to rather than clobbered."""
    saved = {}
    for key, value in CONFIG_CHILD_ENV.get(name, {}).items():
        prior = os.environ.get(key)
        saved[key] = prior
        if key == "XLA_FLAGS" and prior:
            if "xla_force_host_platform_device_count" in prior:
                continue
            value = f"{prior} {value}"
        os.environ[key] = value

    def restore():
        for key, prior in saved.items():
            if prior is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prior

    return restore


def _bench_child(payload: dict):
    """``IsolatedRunner`` child entry: run ONE config and ship its
    detail dict back over the pipe. Each config is self-warming, so a
    fresh process per config pays only the import+compile it already
    paid — and a SIGSEGV in one config can no longer take out the
    artifact: its status lands in the JSON and the next config runs in
    a clean process."""
    return CONFIG_REGISTRY[payload["name"]](payload["args"])


def _tpcds_like(num_rows: int, num_cols: int, seed: int):
    """A store_sales-shaped synthetic table: ~60% numeric measures,
    ~20% integral keys, ~20% low-cardinality categorical strings."""
    import pyarrow as pa

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(seed)
    cols = {}
    n_num = max(1, int(num_cols * 0.6))
    n_key = max(1, int(num_cols * 0.2))
    n_cat = max(1, num_cols - n_num - n_key)
    for i in range(n_num):
        vals = rng.normal(100.0, 25.0, num_rows).astype(np.float32)
        if i % 3 == 0:  # some nulls so masks are real
            idx = rng.integers(0, num_rows, num_rows // 50)
            vals[idx] = np.nan
            arr = pa.array(vals, pa.float32(), mask=np.isnan(vals))
        else:
            arr = pa.array(vals, pa.float32())
        cols[f"m{i}"] = arr
    for i in range(n_key):
        cols[f"k{i}"] = pa.array(
            rng.integers(0, 10_000_000, num_rows, dtype=np.int64)
        )
    cats = np.array([f"cat_{j:03d}" for j in range(64)])
    for i in range(n_cat):
        cols[f"c{i}"] = pa.array(
            cats[rng.integers(0, len(cats), num_rows)]
        ).dictionary_encode()
    return Dataset.from_arrow(pa.table(cols))


def bench_profiler(num_rows: int, num_cols: int):
    """Config 5 / north star: full ColumnProfiler."""
    from deequ_tpu.profiles.profiler import ColumnProfiler

    warm = _tpcds_like(num_rows, num_cols, seed=1)
    warm_s, _, _, _ = _timed(lambda: ColumnProfiler.profile(warm))

    fresh = _tpcds_like(num_rows, num_cols, seed=2)
    wall, shipped, mbps, profiles = _timed(
        lambda: ColumnProfiler.profile(fresh)
    )
    out = {
        "wall_s": wall,
        "cold_s": warm_s,
        "rows_per_sec": num_rows / wall,
        "bytes_shipped": shipped,
        "link_mb_per_sec": mbps,
        "phases": _phases(profiles.run_metadata),
    }
    if profiles.run_metadata is not None:
        out["passes"] = profiles.run_metadata.as_records()
    # steady state: re-profile the SAME dataset (columns device-resident)
    # — separates compute/plan capability from the host->device link,
    # whose bandwidth on tunneled chips swings by orders of magnitude
    resident_wall, resident_shipped, _, _ = _timed(
        lambda: ColumnProfiler.profile(fresh)
    )
    out["resident_rerun_s"] = resident_wall
    out["resident_rows_per_sec"] = num_rows / resident_wall
    out["resident_bytes_shipped"] = resident_shipped
    return out


def bench_fused_bundle(num_rows: int):
    """Config 2: Mean/StdDev/Min/Max/Compliance over 10 numeric cols."""
    import pyarrow as pa

    from deequ_tpu.analyzers import (
        AnalysisRunner,
        Compliance,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
    )
    from deequ_tpu.data import Dataset

    def make(seed):
        rng = np.random.default_rng(seed)
        return Dataset.from_arrow(
            pa.table(
                {
                    f"n{i}": rng.normal(0, 1, num_rows).astype(np.float32)
                    for i in range(10)
                }
            )
        )

    analyzers = []
    for i in range(10):
        analyzers += [
            Mean(f"n{i}"),
            StandardDeviation(f"n{i}"),
            Minimum(f"n{i}"),
            Maximum(f"n{i}"),
        ]
    analyzers.append(Compliance("n0 pos", "n0 > 0"))

    AnalysisRunner.do_analysis_run(make(1), analyzers)  # warm compile
    fresh = make(2)
    wall, shipped, mbps, ctx = _timed(
        lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
    )
    return {
        "wall_s": wall,
        "rows_per_sec": num_rows / wall,
        "bytes_shipped": shipped,
        "link_mb_per_sec": mbps,
        "phases": _phases(ctx.run_metadata),
    }


def bench_grouping(num_rows: int):
    """Config 3: Distinctness + Uniqueness + Histogram on categoricals."""
    import pyarrow as pa

    from deequ_tpu.analyzers import (
        AnalysisRunner,
        Distinctness,
        Histogram,
        Uniqueness,
    )
    from deequ_tpu.data import Dataset

    def make(seed):
        rng = np.random.default_rng(seed)
        cats = np.array([f"v{j}" for j in range(1000)])
        return Dataset.from_arrow(
            pa.table(
                {
                    f"c{i}": pa.array(
                        cats[rng.integers(0, len(cats), num_rows)]
                    ).dictionary_encode()
                    for i in range(5)
                }
            )
        )

    analyzers = []
    for i in range(5):
        analyzers += [
            Distinctness([f"c{i}"]),
            Uniqueness([f"c{i}"]),
            Histogram(f"c{i}"),
        ]

    AnalysisRunner.do_analysis_run(make(1), analyzers)
    fresh = make(2)
    wall, shipped, mbps, ctx = _timed(
        lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
    )
    return {
        "wall_s": wall,
        "rows_per_sec": num_rows / wall,
        "bytes_shipped": shipped,
        "link_mb_per_sec": mbps,
        "phases": _phases(ctx.run_metadata),
    }


def bench_sketches(num_rows: int):
    """Config 4: HLL ApproxCountDistinct + KLL ApproxQuantile, high-card."""
    import pyarrow as pa

    from deequ_tpu.analyzers import (
        AnalysisRunner,
        ApproxCountDistinct,
        ApproxQuantile,
    )
    from deequ_tpu.data import Dataset

    def make(seed):
        rng = np.random.default_rng(seed)
        return Dataset.from_arrow(
            pa.table(
                {
                    "id": rng.integers(0, 1 << 40, num_rows, dtype=np.int64),
                    "x": rng.normal(0, 1, num_rows).astype(np.float32),
                }
            )
        )

    analyzers = [ApproxCountDistinct("id"), ApproxQuantile("x", 0.5)]
    AnalysisRunner.do_analysis_run(make(1), analyzers)
    fresh = make(2)
    wall, shipped, mbps, ctx = _timed(
        lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
    )
    return {
        "wall_s": wall,
        "rows_per_sec": num_rows / wall,
        "bytes_shipped": shipped,
        "link_mb_per_sec": mbps,
        "phases": _phases(ctx.run_metadata),
    }


def _tpcds_faithful(num_rows: int, num_cols: int, seed: int):
    """A store_sales-FAITHFUL wide table: real TPC-DS measures are
    decimal(7,2) prices (cent-quantized, ~10k distinct), small-int
    quantities (1..100), and qty x price extended amounts — NOT
    continuous floats. Mix per 50 cols: 10 price-like, 5 quantity,
    5 ext-amount (high-card), 10 continuous normals (keeps the
    high-cardinality numeric path honest), 10 int keys, 10 categorical
    strings. The 20-col headline keeps `_tpcds_like`'s all-continuous
    measures for round-over-round comparability."""
    import pyarrow as pa

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(seed)
    cols = {}
    n_price = num_cols // 5
    n_qty = num_cols // 10
    n_ext = num_cols // 10
    n_key = num_cols // 5
    n_cat = num_cols // 5
    n_cont = num_cols - n_price - n_qty - n_ext - n_key - n_cat
    for i in range(n_price):
        cents = rng.integers(50, 10_000, num_rows)  # $0.50 .. $99.99
        vals = (cents.astype(np.float32)) / 100
        if i % 3 == 0:
            idx = rng.integers(0, num_rows, num_rows // 50)
            vals[idx] = np.nan
            cols[f"price{i}"] = pa.array(
                vals, pa.float32(), mask=np.isnan(vals)
            )
        else:
            cols[f"price{i}"] = pa.array(vals, pa.float32())
    for i in range(n_qty):
        cols[f"qty{i}"] = pa.array(
            rng.integers(1, 101, num_rows, dtype=np.int64)
        )
    for i in range(n_ext):
        qty = rng.integers(1, 101, num_rows)
        cents = rng.integers(50, 10_000, num_rows)
        cols[f"ext{i}"] = pa.array(
            (qty * cents).astype(np.float32) / 100, pa.float32()
        )
    for i in range(n_cont):
        cols[f"m{i}"] = pa.array(
            rng.normal(100.0, 25.0, num_rows).astype(np.float32),
            pa.float32(),
        )
    for i in range(n_key):
        cols[f"k{i}"] = pa.array(
            rng.integers(0, 10_000_000, num_rows, dtype=np.int64)
        )
    cats = np.array([f"cat_{j:03d}" for j in range(64)])
    for i in range(n_cat):
        cols[f"c{i}"] = pa.array(
            cats[rng.integers(0, len(cats), num_rows)]
        ).dictionary_encode()
    return Dataset.from_arrow(pa.table(cols))


def bench_profiler_wide(num_rows: int, num_cols: int):
    """The NORTH-STAR-shaped config (VERDICT r4 next #2): a first-class
    resident measurement at 50 columns on the store_sales-faithful
    mix, so the 1B x 50 cell-rate claim is measured, not extrapolated.
    cold_s also tracks compile scaling (~300 analyzers)."""
    from deequ_tpu.profiles.profiler import ColumnProfiler

    fresh = _tpcds_faithful(num_rows, num_cols, seed=4)
    # cold_s = compile + transfer together (one dataset keeps this
    # config affordable); a warm-compile link rate would need a second
    # full transfer, and the 20-col headline already measures the link
    # properly — so no link_mb_per_sec here (it would be understated
    # by the ~300-analyzer compile share)
    cold_s, shipped, _, _ = _timed(lambda: ColumnProfiler.profile(fresh))
    # resident reruns: min of two — run 2 has warm registers, so the
    # adaptive mid-cardinality dedup path (sketches/hll.py) is active
    # exactly as it would be on every batch but the first of a 1B run
    r1, _, _, _ = _timed(lambda: ColumnProfiler.profile(fresh))
    r2, _, _, _ = _timed(lambda: ColumnProfiler.profile(fresh))
    resident_wall = min(r1, r2)
    rate = num_rows / resident_wall
    return {
        "cold_compile_plus_transfer_s": cold_s,
        "bytes_shipped": shipped,
        "resident_wall_s": resident_wall,
        "resident_rows_per_sec": rate,
        "ns_per_cell": 1e9 / (rate * num_cols),
        # the link-independent projection: what the 1B x 50 north star
        # costs at THIS chip's measured resident rate on 8 chips
        "projected_1b_x50_resident_8chip_s": 1e9 / (rate * 8),
    }


def bench_spill_grouping(num_rows: int):
    """High-cardinality exact grouping (~num_rows distinct int64 keys):
    the device sort+segment path vs the host Arrow group_by, fresh and
    device-resident."""
    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        CountDistinct,
        Distinctness,
        Uniqueness,
    )
    from deequ_tpu.data import Dataset

    def make(seed):
        import pyarrow as pa

        rng = np.random.default_rng(seed)
        return Dataset.from_arrow(
            pa.table(
                {"id": rng.integers(0, 1 << 40, num_rows, dtype=np.int64)}
            )
        )

    analyzers = [CountDistinct("id"), Uniqueness("id"), Distinctness("id")]
    AnalysisRunner.do_analysis_run(make(5), analyzers)  # warm compile
    fresh = make(6)
    wall, shipped, mbps, ctx = _timed(
        lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
    )
    resident_wall, _, _, _ = _timed(
        lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
    )
    with config.configure(device_spill_grouping=False):
        host_ds = make(6)
        arrow_wall, _, _, _ = _timed(
            lambda: AnalysisRunner.do_analysis_run(host_ds, analyzers)
        )
    spilled = [
        e for e in (ctx.run_metadata.events if ctx.run_metadata else [])
        if e.get("event") == "grouping_spill"
    ]
    return {
        "wall_s": wall,
        "rows_per_sec": num_rows / wall,
        "bytes_shipped": shipped,
        "link_mb_per_sec": mbps,
        "resident_wall_s": resident_wall,
        "resident_rows_per_sec": num_rows / resident_wall,
        "host_arrow_wall_s": arrow_wall,
        "device_vs_arrow_resident": arrow_wall / resident_wall,
        "spill_events": spilled,
    }


def bench_one_pass_grouping(num_rows: int):
    """The one-pass-spill config: a grouping-heavy mixed suite — two
    high-cardinality int id columns and an f64 column under
    Uniqueness / Distinctness / CountDistinct, plus scalar analyzers —
    run with ``config.one_pass_spill`` on (spill key extraction rides
    the shared fused scan, sorts overlap) vs off (one deferred re-scan
    per spill plan). Reports wall AND passes over the source
    (``engine.data_passes``) for each form: the tentpole claim is the
    mixed suite costing exactly ONE traversal."""
    import pyarrow as pa

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        Completeness,
        CountDistinct,
        Distinctness,
        Mean,
        Uniqueness,
    )
    from deequ_tpu.data import Dataset
    from deequ_tpu.telemetry import get_telemetry

    def make(seed):
        rng = np.random.default_rng(seed)
        return Dataset.from_arrow(
            pa.table(
                {
                    "id_a": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "id_b": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "price": rng.normal(0, 1, num_rows),
                    "x": rng.normal(0, 1, num_rows),
                }
            )
        )

    analyzers = [
        Mean("x"),
        Completeness("price"),
        Uniqueness("id_a"),
        Distinctness("id_b"),
        CountDistinct("price"),
    ]

    def passes() -> int:
        snapshot = get_telemetry().metrics.snapshot()
        return snapshot["counters"].get("engine.data_passes", 0)

    out = {}
    for label, one_pass in (("one_pass", True), ("per_plan", False)):
        with config.configure(one_pass_spill=one_pass):
            AnalysisRunner.do_analysis_run(make(31), analyzers)  # warm
            fresh = make(32)
            before = passes()
            wall, shipped, mbps, _ = _timed(
                lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
            )
            out[label] = {
                "wall_s": wall,
                "rows_per_sec": num_rows / wall,
                "passes_over_source": passes() - before,
                "bytes_shipped": shipped,
                "link_mb_per_sec": mbps,
            }
    out["speedup_one_pass"] = (
        out["per_plan"]["wall_s"] / out["one_pass"]["wall_s"]
    )
    return out


def bench_joint_grouping(num_rows: int):
    """r4 config (VERDICT r3 next #7): MutualInformation + Uniqueness
    over a PAIR of ~1M-cardinality int columns (joint key space far
    past the dense budget -> the packed-joint-code device sort), plus
    an f64 high-cardinality column (host-packed u64 keys on TPU, where
    the X64 rewriter lacks the f64 bitcast). Host Arrow comparison
    included."""
    import pyarrow as pa

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        CountDistinct,
        MutualInformation,
        Uniqueness,
    )
    from deequ_tpu.data import Dataset

    def make(seed):
        rng = np.random.default_rng(seed)
        a = rng.integers(0, 1 << 20, num_rows, dtype=np.int64)
        b = np.where(
            rng.random(num_rows) < 0.5,
            a,
            rng.integers(0, 1 << 20, num_rows),
        )
        return Dataset.from_arrow(
            pa.table(
                {
                    "a": pa.array(a),
                    "b": pa.array(b),
                    "f": pa.array(rng.normal(0, 1, num_rows)),
                }
            )
        )

    analyzers = [
        MutualInformation(["a", "b"]),
        Uniqueness(["a", "b"]),
        CountDistinct("f"),
    ]
    AnalysisRunner.do_analysis_run(make(21), analyzers)  # warm compile
    fresh = make(22)
    wall, shipped, mbps, ctx = _timed(
        lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
    )
    with config.configure(device_spill_grouping=False):
        arrow_wall, _, _, _ = _timed(
            lambda: AnalysisRunner.do_analysis_run(make(22), analyzers)
        )
    events = [
        e
        for e in (ctx.run_metadata.events if ctx.run_metadata else [])
        if e.get("event") == "grouping_spill"
    ]
    return {
        "wall_s": wall,
        "rows_per_sec": num_rows / wall,
        "bytes_shipped": shipped,
        "link_mb_per_sec": mbps,
        "host_arrow_wall_s": arrow_wall,
        "device_vs_arrow": arrow_wall / wall,
        "spill_events": events,
    }


def bench_streaming_parquet(num_rows: int, num_cols: int):
    """Streaming ingest config: profile a multi-file parquet table with
    the device cache disabled — memory stays O(batch), every byte
    re-streams from storage through the packed-mask wire diet."""
    import shutil
    import tempfile

    import pyarrow.parquet as pq

    from deequ_tpu import config
    from deequ_tpu.data import Dataset
    from deequ_tpu.profiles.profiler import ColumnProfiler

    workdir = tempfile.mkdtemp(prefix="deequ_tpu_bench_pq_")
    try:
        ds = _tpcds_like(num_rows, num_cols, seed=7)
        shard_rows = num_rows // 4
        for i in range(4):
            # the last shard takes the remainder so every row lands
            length = None if i == 3 else shard_rows
            pq.write_table(
                ds.table.slice(i * shard_rows, length),
                f"{workdir}/part{i}.parquet",
            )
        with config.configure(device_cache_bytes=0, batch_size=1 << 19):
            ColumnProfiler.profile(Dataset.from_parquet(workdir))  # warm
            wall, shipped, mbps, profiles = _timed(
                lambda: ColumnProfiler.profile(Dataset.from_parquet(workdir))
            )
        return {
            "wall_s": wall,
            "rows_per_sec": num_rows / wall,
            "bytes_shipped": shipped,
            "link_mb_per_sec": mbps,
            "phases": _phases(profiles.run_metadata),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_streaming_wire_diet(num_rows: int = 4_000_000):
    """Wire-diet config (docs/PERF.md): the SAME multi-file parquet
    table streamed twice — per-column codecs + one-pass dictionary
    deltas ON vs OFF — so the bytes/row reduction and the put/compute
    overlap of the depth-2 pipeline are measured differentially on
    identical data. The table is codec-friendly on purpose: int64 keys
    whose stats admit i16/i32, f64 measures that are f32-exact, and
    dictionary strings (codes + deltas instead of a value pre-pass)."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        ApproxCountDistinct,
        DataType,
        Maximum,
        Mean,
        Minimum,
    )
    from deequ_tpu.data import Dataset
    from deequ_tpu.telemetry import get_telemetry

    rng = np.random.default_rng(17)
    workdir = tempfile.mkdtemp(prefix="deequ_tpu_bench_wire_")
    # the string suite pairs ACD + DataType on BOTH columns so the
    # codes ride one pooled unit: deltas on = ONE traversal of the
    # source; deltas off re-reads each column once for its value_set
    # (data_passes 1 vs 3 in the artifact)
    analyzers = [
        Mean("f0"), Minimum("f0"), Maximum("f0"), Mean("f1"),
        Minimum("k0"), Maximum("k0"), ApproxCountDistinct("k1"),
        ApproxCountDistinct("k2"),
        ApproxCountDistinct("s0"), ApproxCountDistinct("s1"),
        DataType("s0"), DataType("s1"),
    ]
    try:
        shard_rows = num_rows // 4
        cats = np.array([f"cat_{j:04d}" for j in range(512)])
        for i in range(4):
            rows = num_rows - 3 * shard_rows if i == 3 else shard_rows
            # f32-exact doubles: generate as f32, store as f64
            f = rng.normal(100.0, 25.0, rows).astype(np.float32)
            pq.write_table(
                pa.table(
                    {
                        "f0": pa.array(f.astype(np.float64)),
                        "f1": pa.array(
                            np.abs(f).astype(np.float64)
                        ),
                        "k0": pa.array(
                            rng.integers(0, 30_000, rows, dtype=np.int64)
                        ),
                        "k1": pa.array(
                            rng.integers(0, 100, rows, dtype=np.int64)
                        ),
                        "k2": pa.array(
                            rng.integers(0, 2, rows, dtype=np.int64)
                        ),
                        "s0": pa.array(
                            cats[rng.integers(0, len(cats), rows)]
                        ),
                        "s1": pa.array(
                            cats[rng.integers(0, 64, rows)]
                        ),
                    }
                ),
                f"{workdir}/part{i}.parquet",
            )

        tm = get_telemetry()

        def run(codecs_on: bool):
            with config.configure(
                device_cache_bytes=0,
                batch_size=1 << 19,
                wire_codecs=codecs_on,
                dict_deltas=codecs_on,
            ):
                AnalysisRunner.do_analysis_run(  # warm the plan
                    Dataset.from_parquet(workdir), analyzers
                )
                raw0 = tm.counter("engine.wire_bytes_raw").value
                enc0 = tm.counter("engine.wire_bytes_encoded").value
                passes0 = tm.counter("engine.data_passes").value
                wall, shipped, mbps, ctx = _timed(
                    lambda: AnalysisRunner.do_analysis_run(
                        Dataset.from_parquet(workdir), analyzers
                    )
                )
                return {
                    "wall_s": wall,
                    "rows_per_sec": num_rows / wall,
                    "bytes_shipped": shipped,
                    "link_mb_per_sec": mbps,
                    "raw_bytes_per_row": (
                        tm.counter("engine.wire_bytes_raw").value - raw0
                    ) / num_rows,
                    "encoded_bytes_per_row": (
                        tm.counter("engine.wire_bytes_encoded").value
                        - enc0
                    ) / num_rows,
                    "data_passes": (
                        tm.counter("engine.data_passes").value - passes0
                    ),
                    "phases": _phases(ctx.run_metadata),
                }

        on = run(True)
        off = run(False)
        return {
            "codecs_on": on,
            "codecs_off": off,
            "bytes_per_row_reduction": (
                off["encoded_bytes_per_row"] / on["encoded_bytes_per_row"]
                if on["encoded_bytes_per_row"] > 0
                else 0.0
            ),
            "wall_speedup": off["wall_s"] / on["wall_s"],
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_streaming_ingest_parallel(
    num_rows: int = 4_000_000, num_cols: int = 10
):
    """Parallel-ingest config (docs/PERF.md r10): the SAME multi-file
    parquet table streamed at ingest_workers ∈ {1, 2, 4} — workers=1
    is the legacy single-prefetcher oracle, workers>1 the ordered
    decode/encode pool — so the wall delta is attributable to host
    decode overlap alone. The analyzer suite is one-pass on purpose
    (scalars + codes-borne ACD/DataType; no dictionary materializer)
    and the artifact pins data_passes == 1 per run plus bit-identical
    metrics across worker counts. NOTE the host matters: the pool
    overlaps HOST decode across cores, so on a 1-core container the
    w4/w1 speedup reads ~1.0x by construction — host_cpu_count is in
    the artifact so the verdict can tell a regression from a small
    host."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        ApproxCountDistinct,
        Completeness,
        DataType,
        Maximum,
        Mean,
        Minimum,
    )
    from deequ_tpu.data import Dataset
    from deequ_tpu.telemetry import get_telemetry

    rng = np.random.default_rng(23)
    workdir = tempfile.mkdtemp(prefix="deequ_tpu_bench_ingest_")
    analyzers = [
        Mean("f0"), Minimum("f0"), Maximum("f0"),
        Mean("f1"), Completeness("f2"),
        Minimum("k0"), Maximum("k1"), ApproxCountDistinct("k2"),
        # ACD + DataType PAIRED per string column: the pair rides one
        # pooled codes unit inside the single pass; a lone string
        # analyzer would trigger the dictionary pre-pass and break the
        # data_passes == 1 pin this config asserts
        ApproxCountDistinct("s0"), DataType("s0"),
        ApproxCountDistinct("s1"), DataType("s1"),
    ]
    try:
        shard_rows = num_rows // 4
        cats = np.array([f"cat_{j:04d}" for j in range(512)])
        for i in range(4):
            rows = num_rows - 3 * shard_rows if i == 3 else shard_rows
            f = rng.normal(100.0, 25.0, rows).astype(np.float32)
            f2 = f.astype(np.float64)
            f2[rng.integers(0, rows, rows // 50)] = np.nan
            pq.write_table(
                pa.table(
                    {
                        "f0": pa.array(f.astype(np.float64)),
                        "f1": pa.array(np.abs(f).astype(np.float64)),
                        "f2": pa.array(f2, mask=np.isnan(f2)),
                        "k0": pa.array(
                            rng.integers(0, 30_000, rows, dtype=np.int64)
                        ),
                        "k1": pa.array(
                            rng.integers(0, 100, rows, dtype=np.int64)
                        ),
                        "k2": pa.array(
                            rng.integers(0, 1 << 20, rows, dtype=np.int64)
                        ),
                        "s0": pa.array(
                            cats[rng.integers(0, len(cats), rows)]
                        ),
                        "s1": pa.array(cats[rng.integers(0, 64, rows)]),
                    }
                ),
                f"{workdir}/part{i}.parquet",
            )

        tm = get_telemetry()

        def run(workers: int):
            with config.configure(
                device_cache_bytes=0,
                batch_size=1 << 19,
                wire_codecs=True,
                dict_deltas=True,
                ingest_workers=workers,
            ):
                AnalysisRunner.do_analysis_run(  # warm the plan
                    Dataset.from_parquet(workdir), analyzers
                )
                passes0 = tm.counter("engine.data_passes").value
                wall, shipped, mbps, ctx = _timed(
                    lambda: AnalysisRunner.do_analysis_run(
                        Dataset.from_parquet(workdir), analyzers
                    )
                )
                events = (
                    ctx.run_metadata.events if ctx.run_metadata else []
                )
                pool = {}
                for e in events:
                    if e.get("event") == "ingest_pool":
                        for k in (
                            "workers", "released", "decode_s",
                            "encode_s", "idle_s", "stall_s", "wall_s",
                            "peak_in_flight", "peak_in_flight_bytes",
                        ):
                            pool[k] = pool.get(k, 0) + e.get(k, 0)
                phases = _phases(ctx.run_metadata)
                out = {
                    "wall_s": wall,
                    "rows_per_sec": num_rows / wall,
                    "link_mb_per_sec": mbps,
                    "data_passes": (
                        tm.counter("engine.data_passes").value - passes0
                    ),
                    # decode wall vs run wall: >1x aggregate decode_s
                    # per wall second means the pool really overlapped
                    "host_wait_s": phases.get("host_wait_s", 0.0),
                    "phases": phases,
                }
                if pool:
                    out["pool"] = pool
                    out["decode_overlap_x"] = (
                        (pool["decode_s"] + pool["encode_s"]) / wall
                        if wall > 0 else 0.0
                    )
                metrics = {
                    (m.instance, m.name): m.value
                    for m in ctx.all_metrics()
                }
                return out, metrics

        results = {}
        baselines = None
        identical = True
        for w in (1, 2, 4):
            results[f"workers_{w}"], metrics = run(w)
            if baselines is None:
                baselines = metrics
            elif metrics != baselines:
                identical = False
        w1 = results["workers_1"]["wall_s"]
        return {
            **results,
            "metrics_identical_across_workers": identical,
            "speedup_w2": w1 / results["workers_2"]["wall_s"],
            "speedup_w4": w1 / results["workers_4"]["wall_s"],
            "host_cpu_count": os.cpu_count(),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_resilience_overhead(num_rows: int = 4_000_000):
    """Resilience tax on a CLEAN scan (docs/RESILIENCE.md): the same
    streaming fused-bundle run with retry + periodic checkpointing ON
    (ScanCheckpointer to local disk, every 2 batches) vs OFF
    (max_attempts=1, no checkpointer). No faults fire — this prices the
    bookkeeping alone: per-batch try dispatch, device_get of carried
    states at each checkpoint, and the pickle+fsync. Reported as pct
    overhead over the unprotected wall."""
    import shutil
    import tempfile

    import pyarrow as pa

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        Compliance,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
    )
    from deequ_tpu.data import Dataset
    from deequ_tpu.engine.resilience import RetryPolicy
    from deequ_tpu.engine.scan import AnalysisEngine
    from deequ_tpu.io.state_provider import ScanCheckpointer
    from deequ_tpu.telemetry import get_telemetry

    def make(seed):
        rng = np.random.default_rng(seed)
        return Dataset.from_arrow(
            pa.table(
                {
                    f"n{i}": rng.normal(0, 1, num_rows).astype(np.float32)
                    for i in range(10)
                }
            )
        )

    analyzers = []
    for i in range(10):
        analyzers += [
            Mean(f"n{i}"),
            StandardDeviation(f"n{i}"),
            Minimum(f"n{i}"),
            Maximum(f"n{i}"),
        ]
    analyzers.append(Compliance("n0 pos", "n0 > 0"))

    workdir = tempfile.mkdtemp(prefix="deequ_tpu_bench_ckpt_")
    try:
        with config.configure(device_cache_bytes=0, batch_size=1 << 19):
            AnalysisRunner.do_analysis_run(make(41), analyzers)  # warm
            fresh = make(42)
            with config.configure(
                scan_retry=RetryPolicy(max_attempts=1)
            ):
                off_wall, _, _, _ = _timed(
                    lambda: AnalysisRunner.do_analysis_run(
                        fresh, analyzers
                    )
                )
            tm = get_telemetry()
            ckpts_before = tm.counter("engine.checkpoints_written").value
            with config.configure(checkpoint_every_batches=2):
                engine = AnalysisEngine(
                    checkpointer=ScanCheckpointer(workdir)
                )
                on_wall, _, _, _ = _timed(
                    lambda: AnalysisRunner.do_analysis_run(
                        fresh, analyzers, engine=engine
                    )
                )
            ckpts = tm.counter("engine.checkpoints_written").value
        return {
            "unprotected_wall_s": off_wall,
            "protected_wall_s": on_wall,
            "checkpoints_written": ckpts - ckpts_before,
            "overhead_pct": round(
                100.0 * (on_wall - off_wall) / off_wall, 2
            ) if off_wall > 0 else 0.0,
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_memory_backoff_overhead(num_rows: int = 4_000_000):
    """Memory-protection tax on a CLEAN scan (docs/RESILIENCE.md
    "Memory pressure"): the same streaming fused-bundle run with the
    adaptive batch backoff armed (config.memory_backoff, the default)
    vs disabled. No allocation failure fires — this prices the
    machinery alone: the per-dispatch try frame, the backoff controller
    checks, and the effective-batch gauge. Acceptance bar is <2%
    overhead (a clean run must not pay for protection it never uses)."""
    import pyarrow as pa

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        Compliance,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
    )
    from deequ_tpu.data import Dataset

    def make(seed):
        rng = np.random.default_rng(seed)
        return Dataset.from_arrow(
            pa.table(
                {
                    f"n{i}": rng.normal(0, 1, num_rows).astype(np.float32)
                    for i in range(10)
                }
            )
        )

    analyzers = []
    for i in range(10):
        analyzers += [
            Mean(f"n{i}"),
            StandardDeviation(f"n{i}"),
            Minimum(f"n{i}"),
            Maximum(f"n{i}"),
        ]
    analyzers.append(Compliance("n0 pos", "n0 > 0"))

    with config.configure(device_cache_bytes=0, batch_size=1 << 19):
        AnalysisRunner.do_analysis_run(make(41), analyzers)  # warm
        fresh = make(42)
        with config.configure(memory_backoff=False):
            off_wall, _, _, _ = _timed(
                lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
            )
        with config.configure(memory_backoff=True):
            on_wall, _, _, _ = _timed(
                lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
            )
    return {
        "unprotected_wall_s": off_wall,
        "protected_wall_s": on_wall,
        "overhead_pct": round(
            100.0 * (on_wall - off_wall) / off_wall, 2
        ) if off_wall > 0 else 0.0,
    }


def bench_watchdog_overhead(num_rows: int = 4_000_000):
    """Supervision tax on a CLEAN scan (docs/RESILIENCE.md): the same
    streaming fused-bundle run with a run budget armed (watchdog thread
    polling, per-batch deadline/stall checks, supervised prefetch queue
    polls) vs fully unsupervised. No stall or deadline fires — this
    prices the monitoring alone; the acceptance bar is <2% overhead."""
    import pyarrow as pa

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        Compliance,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
    )
    from deequ_tpu.data import Dataset
    from deequ_tpu.engine.deadline import RunBudget
    from deequ_tpu.engine.scan import AnalysisEngine

    def make(seed):
        rng = np.random.default_rng(seed)
        return Dataset.from_arrow(
            pa.table(
                {
                    f"n{i}": rng.normal(0, 1, num_rows).astype(np.float32)
                    for i in range(10)
                }
            )
        )

    analyzers = []
    for i in range(10):
        analyzers += [
            Mean(f"n{i}"),
            StandardDeviation(f"n{i}"),
            Minimum(f"n{i}"),
            Maximum(f"n{i}"),
        ]
    analyzers.append(Compliance("n0 pos", "n0 > 0"))

    with config.configure(device_cache_bytes=0, batch_size=1 << 19):
        AnalysisRunner.do_analysis_run(make(41), analyzers)  # warm
        fresh = make(42)
        off_wall, _, _, _ = _timed(
            lambda: AnalysisRunner.do_analysis_run(fresh, analyzers)
        )
        # generous limits: the watchdog is armed and polling but never
        # fires, so the delta is pure supervision machinery
        engine = AnalysisEngine(
            budget=RunBudget(deadline_s=3600.0, stall_s=600.0)
        )
        on_wall, _, _, _ = _timed(
            lambda: AnalysisRunner.do_analysis_run(
                fresh, analyzers, engine=engine
            )
        )
    return {
        "unsupervised_wall_s": off_wall,
        "supervised_wall_s": on_wall,
        "overhead_pct": round(
            100.0 * (on_wall - off_wall) / off_wall, 2
        ) if off_wall > 0 else 0.0,
    }


def _probe_link_mb_per_sec() -> float:
    """The tunnel's host->device bandwidth: the MIN of two 32 MB
    transfers (forced by fetches of a device reduction) — a single
    sample on a link that swings minute-to-minute over-sizes the run
    too easily; the min is the conservative sizing input."""
    import jax

    rng = np.random.default_rng(0)
    payloads = [rng.random(4_000_000) for _ in range(3)]  # 32 MB each
    jitted = jax.jit(lambda x: x.sum())
    float(jitted(jax.device_put(payloads[0])))  # warm the compile
    worst = float("inf")
    for payload in payloads[1:]:
        t0 = time.time()
        float(jitted(jax.device_put(payload)))
        worst = min(
            worst, payload.nbytes / max(time.time() - t0, 1e-9) / 1e6
        )
    return worst


def bench_service_concurrent_suites(
    num_rows: int = 2_000_000, clients: int = 8
):
    """Multi-tenant service throughput (PR 7, docs/SERVICE.md): N
    clients across two tenants with mixed priorities verify ONE shared
    dataset key through a warm ``VerificationService``. Prices the
    whole service path — queue, scheduler, shared dataset cache, plan
    reuse — against the same suite run back-to-back directly. Reports
    recompiles-after-warmup (must be 0), dataset placements (must be
    1), and queue-wait p50/p99."""
    import threading

    import pyarrow as pa

    from deequ_tpu import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.service import (
        Priority,
        RunRequest,
        VerificationService,
    )
    from deequ_tpu.telemetry import get_telemetry

    schema = {
        "k1": "int64",
        "k2": "int64",
        "v1": "float32",
        "v2": "float32",
    }

    def make():
        rng = np.random.default_rng(5)
        return Dataset.from_arrow(
            pa.table(
                {
                    "k1": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "k2": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "v1": rng.normal(0, 1, num_rows).astype(np.float32),
                    "v2": rng.normal(0, 1, num_rows).astype(np.float32),
                }
            )
        )

    def checks():
        return [
            Check(CheckLevel.ERROR, "bench-suite")
            .is_complete("k1")
            .is_complete("v1")
            .is_non_negative("k2")
        ]

    tm = get_telemetry()
    svc = VerificationService(workers=2, interactive_reserve=1).start()
    try:
        warm_wall = time.time()
        svc.warmup(
            schema,
            checks=checks(),
            profile=False,
            nullable=(False,),
            wide_ints=(True,),
            batch_size=min(num_rows, 1 << 21),
            engine_variants=[{}],
        )
        warm_wall = time.time() - warm_wall
        compiles_before = tm.counter("engine.plan_cache.misses").value
        placements_before = tm.counter(
            "service.dataset_cache.misses"
        ).value

        handles = []
        t0 = time.time()
        for i in range(clients):
            handles.append(
                svc.submit(
                    RunRequest(
                        tenant="analytics" if i % 2 else "risk",
                        checks=checks(),
                        dataset_key="bench/shared",
                        dataset_factory=make,
                        priority=(
                            Priority.BATCH
                            if i % 2
                            else Priority.INTERACTIVE
                        ),
                    )
                )
            )
        threads = [
            threading.Thread(target=h.wait, args=(600,))
            for h in handles
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        wall = time.time() - t0

        waits = sorted(
            h.started_at - h.submitted_at for h in handles
        )
        compiles = (
            tm.counter("engine.plan_cache.misses").value
            - compiles_before
        )
        placements = (
            tm.counter("service.dataset_cache.misses").value
            - placements_before
        )
        return {
            "clients": clients,
            "rows": num_rows,
            "warmup_wall_s": round(warm_wall, 3),
            "wall_s": round(wall, 3),
            "runs_per_sec": round(clients / wall, 3) if wall else 0.0,
            "recompiles_after_warmup": compiles,
            "dataset_placements": placements,
            "queue_wait_p50_s": round(waits[len(waits) // 2], 4),
            "queue_wait_p99_s": round(waits[-1], 4),
        }
    finally:
        svc.stop(drain=False, timeout=30)


def bench_service_coalesced_suites(
    num_rows: int = 2_000_000, clients: int = 4
):
    """Scan coalescing (docs/SERVICE.md "Scan coalescing"): K
    overlapping BATCH suites against ONE shared dataset key, run twice
    through otherwise-identical services — coalescing OFF then ON.
    The ON phase must show ``engine.data_passes`` collapse from ~K to
    ~1 while per-run results stay identical; two INTERACTIVE gate runs
    ride along in each phase so the queue-wait split by priority class
    shows coalescing never taxes the interactive path (the ISSUE's
    acceptance criterion). Suites are submitted BEFORE the workers
    start (window 0): the first pop atomically absorbs every queued
    compatible ticket, so grouping is deterministic, not racy."""
    import threading

    import pyarrow as pa

    from deequ_tpu import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.service import (
        Priority,
        RunRequest,
        VerificationService,
    )
    from deequ_tpu.telemetry import get_telemetry

    def make():
        rng = np.random.default_rng(5)
        return Dataset.from_arrow(
            pa.table(
                {
                    "k1": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "k2": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "v1": rng.normal(0, 1, num_rows).astype(np.float32),
                    "v2": rng.normal(0, 1, num_rows).astype(np.float32),
                }
            )
        )

    def suite(i):
        # K overlapping tenant suites: everyone wants completeness on
        # k1; the rest differs per tenant, so the superset is a real
        # union, not K copies of one suite
        check = Check(CheckLevel.ERROR, f"tenant-suite-{i}").is_complete(
            "k1"
        )
        if i % 2 == 0:
            check = check.is_complete("v1").is_non_negative("k2")
        else:
            check = check.is_complete("v2")
        return [check]

    def gate():
        return [
            Check(CheckLevel.ERROR, "gate").is_complete("v1")
        ]

    tm = get_telemetry()

    def phase(coalesce_on: bool):
        svc = VerificationService(
            workers=2,
            interactive_reserve=1,
            coalesce=coalesce_on,
            coalesce_window_s=0.0,
        )
        batch = [
            svc.submit(
                RunRequest(
                    tenant=f"tenant-{i}",
                    checks=suite(i),
                    dataset_key="bench/coalesce",
                    dataset_factory=make,
                    priority=Priority.BATCH,
                )
            )
            for i in range(clients)
        ]
        inter = [
            svc.submit(
                RunRequest(
                    tenant="risk",
                    checks=gate(),
                    dataset_key="bench/coalesce",
                    dataset_factory=make,
                    priority=Priority.INTERACTIVE,
                )
            )
            for _ in range(2)
        ]
        passes_before = tm.counter("engine.data_passes").value
        t0 = time.time()
        svc.start()
        try:
            threads = [
                threading.Thread(target=h.wait, args=(600,))
                for h in batch + inter
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            wall = time.time() - t0
        finally:
            svc.stop(drain=False, timeout=30)
        passes = tm.counter("engine.data_passes").value - passes_before

        def waits(handles):
            return sorted(
                max(0.0, h.started_at - h.submitted_at) for h in handles
            )
        batch_waits = waits(batch)
        inter_waits = waits(inter)
        total = len(batch) + len(inter)
        return {
            "wall_s": round(wall, 3),
            "runs_per_sec": round(total / wall, 3) if wall else 0.0,
            "data_passes": int(passes),
            "batch_wait_p50_s": round(
                batch_waits[len(batch_waits) // 2], 4
            ),
            "batch_wait_p99_s": round(batch_waits[-1], 4),
            "interactive_wait_p50_s": round(
                inter_waits[len(inter_waits) // 2], 4
            ),
            "interactive_wait_p99_s": round(inter_waits[-1], 4),
        }

    saved_before = tm.counter("service.scan_passes_saved").value
    off = phase(False)
    on = phase(True)
    saved = tm.counter("service.scan_passes_saved").value - saved_before
    return {
        "rows": num_rows,
        "clients": clients,
        "off": off,
        "on": on,
        "scan_passes_saved": int(saved),
        "data_passes_off": off["data_passes"],
        "data_passes_on": on["data_passes"],
        "speedup": (
            round(off["wall_s"] / on["wall_s"], 3)
            if on["wall_s"]
            else 0.0
        ),
    }


def bench_service_elastic_placement(
    num_rows: int = 1_000_000, clients: int = 4
):
    """Elastic device placement (docs/SERVICE.md "Elastic placement"):
    K concurrent small suites — each on its OWN dataset key, so they
    never coalesce — run twice through otherwise-identical services.
    The ELASTIC arm uses the default policy (small footprints lease
    1-device sub-slices, so runs overlap on disjoint devices); the
    WHOLE-MESH arm pins every lease to the full pool, so runs
    serialize on the lease. Both arms replay plans warmed beforehand
    (the process-global shape-keyed plan cache), so the measured
    recompiles-after-warmup must be 0; every run's metrics must be
    bit-equal to the solo whole-mesh reference. The config needs a
    multi-device pool — the parent injects
    ``--xla_force_host_platform_device_count=8`` into the child's
    environment (CONFIG_CHILD_ENV); a 1-device pool still returns
    rc=0 with the degenerate numbers reported."""
    import threading

    import jax
    import pyarrow as pa

    from deequ_tpu import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.service import (
        ElasticPlacer,
        PlacementPolicy,
        Priority,
        RunRequest,
        VerificationService,
    )
    from deequ_tpu.telemetry import get_telemetry

    pool_total = jax.device_count()

    def make():
        # one seed for every tenant: identical data, so every run's
        # metrics — elastic slice, whole-mesh slice, solo reference —
        # must be BIT-equal, whatever the placement chose
        rng = np.random.default_rng(11)
        return Dataset.from_arrow(
            pa.table(
                {
                    "k1": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "v1": rng.normal(0, 1, num_rows).astype(np.float32),
                    "v2": rng.normal(0, 1, num_rows).astype(np.float32),
                }
            )
        )

    def suite():
        return [
            Check(CheckLevel.ERROR, "elastic-suite")
            .is_complete("k1")
            .is_non_negative("k1")
            .is_complete("v1")
        ]

    def fingerprint(result):
        # exact metric values (repr keeps every float bit) keyed by
        # analyzer — the bit-equality pin across placements
        return tuple(
            sorted(
                (str(analyzer), repr(getattr(metric, "value", metric)))
                for analyzer, metric in dict(result.metrics).items()
            )
        )

    whole_mesh_placer = lambda: ElasticPlacer(  # noqa: E731
        policy=PlacementPolicy(
            bytes_per_device=1, default_devices=pool_total
        )
    )

    def run_phase(svc, label):
        handles = [
            svc.submit(
                RunRequest(
                    tenant=f"tenant-{i}",
                    checks=suite(),
                    dataset_key=f"bench/elastic/{label}/{i}",
                    dataset_factory=make,
                    priority=Priority.BATCH,
                )
            )
            for i in range(clients)
        ]
        t0 = time.time()
        svc.start()
        try:
            threads = [
                threading.Thread(target=h.wait, args=(600,))
                for h in handles
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            wall = time.time() - t0
        finally:
            svc.stop(drain=False, timeout=30)
        waits = sorted(
            max(0.0, (h.started_at or 0.0) - h.submitted_at)
            for h in handles
        )
        spans = [
            (
                h.started_at or 0.0,
                h.finished_at or 0.0,
                (h.placement or {}).get("ndev") or pool_total,
                tuple((h.placement or {}).get("device_ids") or ()),
            )
            for h in handles
        ]
        # peak placement concurrency: at each run start, how many runs
        # were live at once — the leases guarantee their device sets
        # are pairwise disjoint, which the artifact double-checks
        max_live, disjoint = 0, True
        for s0, _f0, _n0, _d0 in spans:
            live = [
                d
                for s, f, _n, d in spans
                if s <= s0 < f
            ]
            if len(live) > max_live:
                max_live = len(live)
                seen: set = set()
                for dev_ids in live:
                    if seen.intersection(dev_ids):
                        disjoint = False
                    seen.update(dev_ids)
        busy = sum((f - s) * n for s, f, n, _d in spans)
        return {
            "wall_s": round(wall, 3),
            "wait_p50_s": round(waits[len(waits) // 2], 4),
            "wait_p99_s": round(waits[-1], 4),
            "max_concurrent": max_live,
            "slices_disjoint": disjoint,
            "device_busy_fraction": round(
                busy / (wall * pool_total), 4
            )
            if wall
            else 0.0,
            "placements": [
                {"ndev": n, "device_ids": list(d)}
                for _s, _f, n, d in spans
            ],
        }, [h.result(timeout=0) for h in handles]

    tm = get_telemetry()

    # solo whole-mesh reference: one run on the full pool — the
    # bit-equality baseline; it also compiles the whole-mesh shape
    solo_svc = VerificationService(
        workers=1, isolated=False, coalesce=False,
        placer=whole_mesh_placer(),
    )
    _stats, solo_results = run_phase(solo_svc, "solo")
    solo_print = fingerprint(solo_results[0])

    # warm the elastic shapes (untimed): same K submissions through an
    # identical elastic service populate the process-global shape-keyed
    # plan cache, so the measured arms below replay, never compile
    warm_svc = VerificationService(
        workers=clients, isolated=False, coalesce=False,
        elastic_placement=True,
    )
    run_phase(warm_svc, "warm")

    misses_before = tm.counter("engine.plan_cache.misses").value
    elastic_svc = VerificationService(
        workers=clients, isolated=False, coalesce=False,
        elastic_placement=True,
    )
    elastic, elastic_results = run_phase(elastic_svc, "elastic")
    whole_svc = VerificationService(
        workers=clients, isolated=False, coalesce=False,
        placer=whole_mesh_placer(),
    )
    whole, whole_results = run_phase(whole_svc, "whole")
    recompiles = tm.counter("engine.plan_cache.misses").value - misses_before

    bit_equal = all(
        fingerprint(r) == solo_print
        for r in elastic_results + whole_results
    )
    return {
        "rows": num_rows,
        "clients": clients,
        "pool_devices": pool_total,
        "elastic": elastic,
        "whole_mesh": whole,
        "recompiles_after_warmup": int(recompiles),
        "metrics_bit_equal": bool(bit_equal),
        "speedup": (
            round(whole["wall_s"] / elastic["wall_s"], 3)
            if elastic["wall_s"]
            else 0.0
        ),
    }


def bench_service_preemption(num_rows: int = 1_000_000, clients: int = 4):
    """Checkpoint-conserving preemption (docs/SERVICE.md "Preemption
    and autoscaling"): K INTERACTIVE suites arrive while long BATCH
    runs saturate a 1-worker pool. With ``preemption=True`` the
    running BATCH victim is cancelled at its next batch boundary
    (final checkpoint persisted), requeued with its cursor, and
    resumed after the interactive burst — so the measured interactive
    p99 queue wait must match the idle-pool p99 (same K interactive
    submissions, no BATCH load) within 10%, work must be conserved
    (extra ``engine.data_passes`` == preemptions: one resumed
    traversal each, which recomputes at most the one in-flight batch),
    and every preempted-then-resumed BATCH result must be bit-equal to
    the uninterrupted solo reference."""
    import tempfile
    import threading
    import time as _time

    import pyarrow as pa

    from deequ_tpu import Check, CheckLevel, config
    from deequ_tpu.data import Dataset
    from deequ_tpu.service import (
        Priority,
        RunRequest,
        VerificationService,
    )
    from deequ_tpu.telemetry import get_telemetry

    def make():
        rng = np.random.default_rng(17)
        return Dataset.from_arrow(
            pa.table(
                {
                    "k1": rng.integers(
                        0, 1 << 40, num_rows, dtype=np.int64
                    ),
                    "v1": rng.normal(0, 1, num_rows).astype(np.float32),
                    "v2": rng.normal(0, 1, num_rows).astype(np.float32),
                }
            )
        )

    def batch_suite():
        return [
            Check(CheckLevel.ERROR, "preempt-batch")
            .is_complete("k1")
            .is_non_negative("k1")
            .is_complete("v1")
            .is_complete("v2")
        ]

    def interactive_suite():
        return [Check(CheckLevel.ERROR, "preempt-inter").is_complete("k1")]

    def fingerprint(result):
        return tuple(
            sorted(
                (str(analyzer), repr(getattr(metric, "value", metric)))
                for analyzer, metric in dict(result.metrics).items()
            )
        )

    def submit(svc, label, i, priority, checks):
        return svc.submit(
            RunRequest(
                tenant=f"tenant-{i}",
                checks=checks,
                dataset_key=f"bench/preempt/{label}/{priority}/{i}",
                dataset_factory=make,
                priority=priority,
            )
        )

    def wait_all(handles, timeout=600):
        threads = [
            threading.Thread(target=h.wait, args=(timeout,))
            for h in handles
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)

    def waits_of(handles):
        return sorted(
            max(0.0, (h.started_at or 0.0) - h.submitted_at)
            for h in handles
        )

    tm = get_telemetry()
    root = tempfile.mkdtemp(prefix="deequ_tpu_bench_preempt_")
    nbatch = 2
    # many small batches => preemption lands quickly at a boundary and
    # the conserved-work claim (cursor skips completed batches) is
    # about real work, not one giant batch
    overrides = dict(
        batch_size=max(4096, num_rows // 16), checkpoint_every_batches=1
    )
    try:
        with config.configure(**overrides):
            # solo uninterrupted BATCH reference: the bit-equality pin
            # (also warms the plan cache for every later arm)
            solo_svc = VerificationService(
                workers=1, isolated=False, coalesce=False,
                preemption=True, journal_dir=f"{root}/solo",
            )
            solo_svc.start()
            try:
                solo = submit(
                    solo_svc, "solo", 0, Priority.BATCH, batch_suite()
                )
                solo.wait(600)
                submit(
                    solo_svc, "solo", 0, Priority.INTERACTIVE,
                    interactive_suite(),
                ).wait(600)  # warm the interactive plan too
            finally:
                solo_svc.stop(drain=False, timeout=30)
            solo_print = fingerprint(solo.result(timeout=0))

            # idle-pool reference: the SAME K interactive submissions
            # on an identical (preemption-enabled) service with no
            # BATCH load — the p99 the saturated arm must match
            idle_svc = VerificationService(
                workers=1, isolated=False, coalesce=False,
                preemption=True, journal_dir=f"{root}/idle",
            )
            idle_svc.start()
            try:
                idle_handles = [
                    submit(
                        idle_svc, "idle", i, Priority.INTERACTIVE,
                        interactive_suite(),
                    )
                    for i in range(clients)
                ]
                wait_all(idle_handles)
            finally:
                idle_svc.stop(drain=False, timeout=30)
            idle_waits = waits_of(idle_handles)

            # saturated arm: BATCH runs own the single worker, THEN the
            # interactive burst arrives and must preempt through
            preempts0 = tm.counter("service.preemptions").value
            resumes0 = tm.counter("service.preempt_resumes").value
            conserved0 = tm.counter(
                "service.preempted_batches_conserved"
            ).value
            passes0 = tm.counter("engine.data_passes").value
            sat_svc = VerificationService(
                workers=1, isolated=False, coalesce=False,
                preemption=True, journal_dir=f"{root}/sat",
            )
            sat_svc.start()
            try:
                batch_handles = [
                    submit(
                        sat_svc, "sat", i, Priority.BATCH, batch_suite()
                    )
                    for i in range(nbatch)
                ]
                deadline = _time.time() + 60
                while (
                    not any(h.started_at for h in batch_handles)
                    and _time.time() < deadline
                ):
                    _time.sleep(0.01)
                inter_handles = [
                    submit(
                        sat_svc, "sat", i, Priority.INTERACTIVE,
                        interactive_suite(),
                    )
                    for i in range(clients)
                ]
                wait_all(inter_handles)
                wait_all(batch_handles)
            finally:
                sat_svc.stop(drain=False, timeout=30)
            sat_waits = waits_of(inter_handles)
            preemptions = int(
                tm.counter("service.preemptions").value - preempts0
            )
            resumes = int(
                tm.counter("service.preempt_resumes").value - resumes0
            )
            conserved = int(
                tm.counter("service.preempted_batches_conserved").value
                - conserved0
            )
            data_passes = int(
                tm.counter("engine.data_passes").value - passes0
            )
    finally:
        import shutil

        shutil.rmtree(root, ignore_errors=True)

    idle_p99 = idle_waits[-1]
    sat_p99 = sat_waits[-1]
    batch_results = [h.result(timeout=0) for h in batch_handles]
    bit_equal = all(
        r is not None and fingerprint(r) == solo_print
        for r in batch_results
    )
    # every preemption costs exactly one extra traversal entry (the
    # resumed pass), whose cursor skips all completed batches
    extra_passes = data_passes - (nbatch + clients)
    return {
        "rows": num_rows,
        "clients": clients,
        "idle_wait_p99_s": round(idle_p99, 4),
        "saturated_wait_p99_s": round(sat_p99, 4),
        # 10% relative plus a small absolute floor: at millisecond
        # scale a single scheduler-thread wakeup would otherwise flip
        # the verdict on noise
        "interactive_p99_within_10pct": bool(
            sat_p99 <= idle_p99 * 1.10 + 0.25
        ),
        "preemptions": preemptions,
        "preempt_resumes": resumes,
        "batches_conserved": conserved,
        "data_passes": data_passes,
        "extra_passes": extra_passes,
        "work_conserved": bool(
            0 <= extra_passes <= max(preemptions, 0)
        ),
        "preempted_results_bit_equal": bool(bit_equal),
    }


def bench_streaming_bundle_100m(num_rows: int = 100_000_000):
    """BASELINE.json config 2 at its SPECIFIED scale, streamed:
    Mean/StdDev/Min/Max/Compliance over 10 numeric f32 columns,
    100M rows read from multi-file parquet with the device cache off —
    nothing above 32M rows had ever executed before r4 (VERDICT r3
    next #2). Generated shard-by-shard so host memory stays bounded;
    the measured run re-streams every byte storage->host->device.

    The run is LINK-BOUND by construction (~40 B/row), and the tunnel
    swings 2-140 MB/s between minutes — at 2 MB/s the full 100M rows
    is a 30+ minute stall. The config therefore probes the link first
    and sizes the row count to a ~240 s wall (capped at 100M), with
    the probe and chosen size disclosed in the output; per-row and
    projection numbers are scale-independent."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from deequ_tpu import config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        Compliance,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
    )
    from deequ_tpu.data import Dataset

    batch = 1 << 21
    probe_mbps = _probe_link_mb_per_sec()
    bytes_per_row = 40.3  # measured (values + packed masks)
    target_wall_s = 240.0
    affordable = int(probe_mbps * 1e6 * target_wall_s / bytes_per_row)
    if affordable < num_rows:  # probe-sized runs keep an 8M floor; an
        # explicit smaller argument is honored as-is
        num_rows = max(8_000_000, affordable)
    # whole 2^21-row batches (= the configured batch size, so no
    # padded tail inflates bytes_per_row and the projection)
    num_rows = max(batch, (num_rows // batch) * batch)

    rng = np.random.default_rng(11)
    workdir = tempfile.mkdtemp(prefix="deequ_tpu_bench_100m_")

    def shard_table(rows: int) -> "pa.Table":
        return pa.table(
            {
                f"n{j}": rng.normal(0.0, 1.0, rows).astype(np.float32)
                for j in range(10)
            }
        )

    try:
        shard_rows = 12_500_000
        gen_t0 = time.time()
        done = 0
        i = 0
        while done < num_rows:
            rows = min(shard_rows, num_rows - done)
            pq.write_table(
                shard_table(rows), f"{workdir}/part{i:02d}.parquet"
            )
            done += rows
            i += 1
        gen_s = time.time() - gen_t0

        analyzers = []
        for j in range(10):
            analyzers += [
                Mean(f"n{j}"),
                StandardDeviation(f"n{j}"),
                Minimum(f"n{j}"),
                Maximum(f"n{j}"),
            ]
        analyzers.append(Compliance("n0 pos", "n0 > 0"))

        with config.configure(device_cache_bytes=0, batch_size=batch):
            # warm the compiles on a tiny same-schema parquet (identical
            # batch shape: the tail batch pads to the same 2M width)
            warmdir = tempfile.mkdtemp(prefix="deequ_tpu_bench_100m_w_")
            try:
                pq.write_table(
                    shard_table(1 << 21), f"{warmdir}/part.parquet"
                )
                AnalysisRunner.do_analysis_run(
                    Dataset.from_parquet(warmdir), analyzers
                )
            finally:
                shutil.rmtree(warmdir, ignore_errors=True)

            wall, shipped, mbps, ctx = _timed(
                lambda: AnalysisRunner.do_analysis_run(
                    Dataset.from_parquet(workdir), analyzers
                )
            )
        bytes_per_row = shipped / num_rows if num_rows else 0.0
        out = {
            "rows": num_rows,
            "link_probe_mb_per_sec": round(probe_mbps, 2),
            "wall_s": wall,
            "rows_per_sec": num_rows / wall,
            "bytes_shipped": shipped,
            "bytes_per_row": round(bytes_per_row, 2),
            "link_mb_per_sec": mbps,
            "gen_parquet_s": gen_s,
            "phases": _phases(ctx.run_metadata),
        }
        # extrapolation to the 1B x 50-col north star, stated as math
        # on THIS config's measurements (VERDICT r3 next #2): 1B rows
        # at 5x the columns ships 5x the bytes/row; v5e-8 divides the
        # stream over 8 chips each with its own host link
        if mbps > 0:
            out["projected_1b_x50_wall_s_link_bound_8chip"] = round(
                1e9 * bytes_per_row * 5 / (mbps * 1e6) / 8, 1
            )
            out["projection_math"] = (
                f"1e9 rows * {bytes_per_row:.1f} B/row * 5 (50/10 cols)"
                f" / {mbps:.1f} MB/s / 8 chips"
            )
        return out
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_rowlevel_egress(num_rows: int = 4_000_000):
    """Row-level egress config (docs/EGRESS.md): the SAME mask/predicate
    suite streamed twice — once with a RowLevelSink splitting every row
    into clean/quarantine parquet, once metrics-only — so the price of
    bytes OUT is measured differentially on identical data: wall
    overhead, outbound bytes/row (raw -> encoded), and the pass
    accounting (both arms must read the source exactly once; the split
    rides the same fused scan the metrics do)."""
    import shutil
    import tempfile

    import pyarrow as pa

    from deequ_tpu import config
    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.egress import RowLevelSink
    from deequ_tpu.telemetry import get_telemetry
    from deequ_tpu.verification.suite import VerificationSuite

    rng = np.random.default_rng(23)
    amount = rng.gamma(2.0, 40.0, num_rows)
    amount[rng.random(num_rows) < 0.01] *= -1.0
    user = rng.integers(0, max(1, num_rows // 50), num_rows)
    domain = np.where(rng.random(num_rows) < 0.05, "bad addr", "ex.com")
    email = np.char.add(
        np.char.add("u", user.astype("U12")), np.char.add("@", domain)
    ).astype(object)
    email[rng.random(num_rows) < 0.02] = None
    data = Dataset.from_arrow(
        pa.table(
            {
                "event_id": pa.array(np.arange(num_rows, dtype=np.int64)),
                "amount": pa.array(amount),
                "email": pa.array(email, type=pa.string()),
            }
        )
    )
    checks = [
        Check(CheckLevel.ERROR, "hygiene")
        .is_complete("email")
        .has_pattern("email", r"@ex\.com$")
        .satisfies("amount >= 0", "amount_non_negative")
    ]
    tm = get_telemetry()
    workdirs = []

    def run(egress_on: bool):
        def once():
            sink = None
            if egress_on:
                out_dir = tempfile.mkdtemp(prefix="deequ_tpu_bench_eg_")
                workdirs.append(out_dir)
                sink = RowLevelSink(out_dir)
            return VerificationSuite.do_verification_run(
                data, checks, row_level_sink=sink
            )

        with config.configure(device_cache_bytes=0):
            once()  # warm the plan; priced runs below are steady-state
            raw0 = tm.counter("engine.egress_bytes_raw").value
            enc0 = tm.counter("engine.egress_bytes_encoded").value
            passes0 = tm.counter("engine.data_passes").value
            wall, _shipped, _mbps, result = _timed(once)
        out = {
            "wall_s": wall,
            "rows_per_sec": num_rows / wall,
            "data_passes": (
                tm.counter("engine.data_passes").value - passes0
            ),
            "egress_raw_bytes_per_row": (
                tm.counter("engine.egress_bytes_raw").value - raw0
            ) / num_rows,
            "egress_encoded_bytes_per_row": (
                tm.counter("engine.egress_bytes_encoded").value - enc0
            ) / num_rows,
        }
        if egress_on:
            report = result.row_level_egress
            out["egress_status"] = report.status
            out["rows_clean"] = report.rows_clean
            out["rows_quarantined"] = report.rows_quarantined
        return out

    try:
        on = run(True)
        off = run(False)
        return {
            "rows": num_rows,
            "egress_on": on,
            "egress_off": off,
            "wall_overhead": (
                on["wall_s"] / off["wall_s"] if off["wall_s"] > 0 else 0.0
            ),
        }
    finally:
        for d in workdirs:
            shutil.rmtree(d, ignore_errors=True)


def bench_egress_resume(num_rows: int = 800_000):
    """Exactly-once egress resume (docs/EGRESS.md "Durable egress"):
    the quarantine suite streamed uninterrupted, then the SAME suite
    killed at its halfway batch and resumed from the durable span
    cursor. The exactly-once claims are priced and pinned in one
    config: the killed+resumed pair must finish within 10% of the
    uninterrupted wall (the resume's cursor skips every durably
    flushed span — only the open span is recomputed),
    ``engine.egress_rows_replayed`` must stay 0, and the published
    clean/quarantine split must be BYTE-equal to the uninterrupted
    artifact."""
    import shutil
    import tempfile

    import pyarrow as pa

    from deequ_tpu import config
    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.egress import RowLevelSink
    from deequ_tpu.engine.resilience import ScanKilled
    from deequ_tpu.engine.scan import AnalysisEngine
    from deequ_tpu.io.state_provider import ScanCheckpointer
    from deequ_tpu.telemetry import get_telemetry
    from deequ_tpu.testing.faults import FaultInjectingDataset
    from deequ_tpu.verification.suite import VerificationSuite

    rng = np.random.default_rng(23)
    amount = rng.gamma(2.0, 40.0, num_rows)
    amount[rng.random(num_rows) < 0.01] *= -1.0
    user = rng.integers(0, max(1, num_rows // 50), num_rows)
    domain = np.where(rng.random(num_rows) < 0.05, "bad addr", "ex.com")
    email = np.char.add(
        np.char.add("u", user.astype("U12")), np.char.add("@", domain)
    ).astype(object)
    email[rng.random(num_rows) < 0.02] = None
    data = Dataset.from_arrow(
        pa.table(
            {
                "event_id": pa.array(np.arange(num_rows, dtype=np.int64)),
                "amount": pa.array(amount),
                "email": pa.array(email, type=pa.string()),
            }
        )
    )
    checks = [
        Check(CheckLevel.ERROR, "hygiene")
        .is_complete("email")
        .has_pattern("email", r"@ex\.com$")
        .satisfies("amount >= 0", "amount_non_negative")
    ]
    batch_size = max(4096, num_rows // 64)
    nbatches = (num_rows + batch_size - 1) // batch_size
    kill_at = nbatches // 2
    tm = get_telemetry()
    root = tempfile.mkdtemp(prefix="deequ_tpu_bench_egresume_")

    def run(arm, ds):
        sink = RowLevelSink(os.path.join(root, arm, "out"))
        engine = AnalysisEngine(
            checkpointer=ScanCheckpointer(os.path.join(root, arm, "ckpt"))
        )
        return VerificationSuite.do_verification_run(
            ds, checks, engine=engine, row_level_sink=sink
        )

    def split_bytes(arm):
        out = {}
        for split in ("clean", "quarantine"):
            path = os.path.join(
                root, arm, "out", split, "part-00000.parquet"
            )
            with open(path, "rb") as fh:
                out[split] = fh.read()
        return out

    try:
        with config.configure(
            device_cache_bytes=0,
            batch_size=batch_size,
            checkpoint_every_batches=4,
        ):
            run("warm", data)  # priced arms below are steady-state
            wall_solo, _, _, solo_result = _timed(lambda: run("solo", data))

            killed_ds = FaultInjectingDataset(data, kill_at_batch=kill_at)
            replayed0 = tm.counter("engine.egress_rows_replayed").value
            resumes0 = tm.counter("engine.resumes").value

            def killed_then_resumed():
                try:
                    run("killed", killed_ds)
                    raise RuntimeError("injected kill never fired")
                except ScanKilled:
                    pass
                # same artifact dir + checkpoint path: the relaunch
                # shape, minus the process spawn (priced elsewhere)
                return run("killed", killed_ds)

            wall_killed, _, _, resumed_result = _timed(killed_then_resumed)
        rows_replayed = int(
            tm.counter("engine.egress_rows_replayed").value - replayed0
        )
        resumes = int(tm.counter("engine.resumes").value - resumes0)
        solo_report = solo_result.row_level_egress
        report = resumed_result.row_level_egress
        byte_equal = split_bytes("solo") == split_bytes("killed")
    finally:
        shutil.rmtree(root, ignore_errors=True)
    added = (
        (wall_killed - wall_solo) / wall_solo if wall_solo > 0 else 0.0
    )
    return {
        "rows": num_rows,
        "batches": nbatches,
        "kill_at_batch": kill_at,
        "wall_uninterrupted_s": round(wall_solo, 3),
        "wall_killed_plus_resume_s": round(wall_killed, 3),
        "added_wall_pct": round(added * 100.0, 2),
        # 10% relative plus a small absolute floor (same rationale as
        # service_preemption: sub-second walls flip on scheduler noise)
        "resume_within_10pct": bool(
            wall_killed <= wall_solo * 1.10 + 0.25
        ),
        "resumes": resumes,
        "rows_replayed": rows_replayed,
        "egress_status": report.status,
        "rows_clean": report.rows_clean,
        "rows_quarantined": report.rows_quarantined,
        "counters_conserved": bool(
            report.rows_clean == solo_report.rows_clean
            and report.rows_quarantined == solo_report.rows_quarantined
            and report.rows_clean + report.rows_quarantined == num_rows
        ),
        "split_byte_equal": bool(byte_equal),
    }


_FLEET_VICTIM_SRC = r"""
import signal, sys
fleet_dir, journal_dir = sys.argv[1], sys.argv[2]
rows, n_runs = int(sys.argv[3]), int(sys.argv[4])
heartbeat_s, lease_timeout_s = float(sys.argv[5]), float(sys.argv[6])
import numpy as np
from deequ_tpu import config
from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.data import Dataset
from deequ_tpu.service import Priority, RunRequest, VerificationService

rng = np.random.default_rng(11)
data = {
    "a": rng.normal(size=rows).tolist(),
    "g": (np.arange(rows) % 7).tolist(),
}
checks = [
    Check(CheckLevel.ERROR, "fleet-bench")
    .has_size(lambda s: s == rows)
    .is_complete("a")
]
with config.configure(
    checkpoint_every_batches=4,
    batch_size=max(4096, rows // 32),
    device_cache_bytes=0,
    service_fleet_heartbeat_s=heartbeat_s,
    service_fleet_lease_timeout_s=lease_timeout_s,
):
    svc = VerificationService(
        workers=1, isolated=False, journal_dir=journal_dir,
        fleet_dir=fleet_dir, replica_id="bench-victim",
    ).start()
    handles = [
        svc.submit(RunRequest(
            tenant="bench", checks=checks,
            dataset_key=f"bench-fleet-{i}",
            dataset_factory=lambda: Dataset.from_pydict(data),
            priority=Priority.STANDARD,
        ))
        for i in range(n_runs)
    ]
    for i, h in enumerate(handles):
        h.wait(timeout=600)
        print(f"DONE {i}", flush=True)  # the parent's SIGKILL trigger
    svc.stop()
print("ALL", flush=True)
"""


def bench_fleet_failover(num_rows: int = 400_000, n_runs: int = 4):
    """Fleet failover under a REAL replica kill (docs/SERVICE.md "Fleet
    failover"): a whole replica process — service, fleet supervisor,
    heartbeat thread, a queue of journaled runs — is SIGKILLed from
    outside at 50% queue progress. A survivor replica in this process
    shares the fleet dir, sees the lease go stale, wins the adoption
    CAS, and replays the orphan's pending runs; the mid-flight run
    resumes from the shared durable checkpoint cursor. Priced and
    pinned: time-to-adoption (~one lease timeout), ``runs_lost`` and
    ``runs_double_persisted`` both 0, and the adopted backlog finishing
    within 10% of uninterrupted cost."""
    import shutil
    import signal as _signal
    import subprocess
    import tempfile

    from deequ_tpu import config
    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.service import RunRequest, RunState, VerificationService
    from deequ_tpu.service.journal import RunJournal
    from deequ_tpu.verification.suite import VerificationSuite

    heartbeat_s, lease_timeout_s = 0.3, 1.2
    kill_after_done = n_runs // 2 - 1  # mid-queue: run n_runs//2 in flight
    root = tempfile.mkdtemp(prefix="deequ_tpu_bench_fleet_")
    fleet_dir = os.path.join(root, "fleet")
    victim_journal = os.path.join(root, "victim-journal")
    survivor_journal = os.path.join(root, "survivor-journal")

    rng = np.random.default_rng(11)  # the victim builds the SAME table
    data = {
        "a": rng.normal(size=num_rows).tolist(),
        "g": (np.arange(num_rows) % 7).tolist(),
    }
    checks = [
        Check(CheckLevel.ERROR, "fleet-bench")
        .has_size(lambda s: s == num_rows)
        .is_complete("a")
    ]
    scan_opts = dict(
        checkpoint_every_batches=4,
        batch_size=max(4096, num_rows // 32),
        device_cache_bytes=0,
        service_fleet_heartbeat_s=heartbeat_s,
        service_fleet_lease_timeout_s=lease_timeout_s,
    )
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)

    try:
        with config.configure(**scan_opts):
            # oracle: one uninterrupted run of the same suite, warmed —
            # the unit the adopted backlog's wall is priced against
            ds = Dataset.from_pydict(data)
            VerificationSuite.do_verification_run(ds, checks)
            wall_solo, _, _, oracle = _timed(
                lambda: VerificationSuite.do_verification_run(ds, checks)
            )

            proc = subprocess.Popen(
                [
                    sys.executable, "-c", _FLEET_VICTIM_SRC,
                    fleet_dir, victim_journal,
                    str(num_rows), str(n_runs),
                    str(heartbeat_s), str(lease_timeout_s),
                ],
                stdout=subprocess.PIPE,
                text=True,
                env=env,
            )
            killed = False
            try:
                for line in proc.stdout:
                    if line.strip() == f"DONE {kill_after_done}":
                        os.kill(proc.pid, _signal.SIGKILL)
                        killed = True
                        break
            finally:
                if not killed and proc.poll() is None:
                    proc.kill()
                proc.wait()
                proc.stdout.close()
            t_kill = time.monotonic()

            victim_records = RunJournal(victim_journal).replay()
            done_before = {
                r["run_id"]
                for r in victim_records
                if r.get("type") == "terminal"
                and r.get("state") == RunState.DONE
            }
            pending_before = RunJournal(victim_journal).pending_runs()

            svc = VerificationService(
                workers=1, isolated=False,
                journal_dir=survivor_journal,
                fleet_dir=fleet_dir,
                replica_id="bench-survivor",
                adopt_resolve=lambda entry: RunRequest(
                    tenant=entry["tenant"],
                    checks=checks,
                    dataset_key=entry.get("dataset_key"),
                    dataset_factory=lambda: Dataset.from_pydict(data),
                ),
            )
            adoptions = []
            adopt_deadline = time.monotonic() + 30.0
            while not adoptions and time.monotonic() < adopt_deadline:
                adoptions = svc.fleet.poll()
                if not adoptions:
                    time.sleep(0.05)
            time_to_adoption = time.monotonic() - t_kill
            adopted = svc.adopted_runs()

            svc.start()
            try:
                t0 = time.monotonic()
                for h in adopted:
                    h.wait(timeout=300)
                wall_adopted = time.monotonic() - t0
                adopted_done = sum(
                    1 for h in adopted if h.status == RunState.DONE
                )
                results_match = all(
                    sorted(
                        (str(a), m.value.get())
                        for a, m in h.result(timeout=0).metrics.items()
                    )
                    == sorted(
                        (str(a), m.value.get())
                        for a, m in oracle.metrics.items()
                    )
                    for h in adopted
                    if h.status == RunState.DONE
                )
            finally:
                svc.stop(drain=False, timeout=10)

            survivor_records = RunJournal(survivor_journal).replay()
            adopted_from = [
                r["adopted_from"]
                for r in survivor_records
                if r.get("type") == "submitted" and r.get("adopted_from")
            ]
        runs_lost = n_runs - len(done_before) - adopted_done
        runs_double_persisted = len(
            set(adopted_from) & done_before
        ) + (len(adopted_from) - len(set(adopted_from)))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    backlog = max(1, len(adopted))
    return {
        "rows": num_rows,
        "runs": n_runs,
        "heartbeat_s": heartbeat_s,
        "lease_timeout_s": lease_timeout_s,
        "victim_killed": bool(killed),
        "runs_done_before_kill": len(done_before),
        "runs_pending_at_kill": len(pending_before),
        "runs_adopted": len(adopted),
        "runs_adopted_done": adopted_done,
        "runs_lost": int(runs_lost),
        "runs_double_persisted": int(runs_double_persisted),
        "time_to_adoption_s": round(time_to_adoption, 3),
        "adoption_within_3x_timeout": bool(
            time_to_adoption <= lease_timeout_s * 3 + 2.0
        ),
        "lease_stale_for_s": (
            round(adoptions[0].stale_for_s, 3) if adoptions else None
        ),
        "wall_uninterrupted_per_run_s": round(wall_solo, 3),
        "wall_adopted_backlog_s": round(wall_adopted, 3),
        # the resumed run skips its checkpointed prefix, so the backlog
        # must land within the uninterrupted cost of the same runs (10%
        # relative + absolute floor, as service_preemption/egress_resume)
        "adopted_within_10pct": bool(
            wall_adopted <= wall_solo * backlog * 1.10 + 0.25
        ),
        "results_match_oracle": bool(results_match),
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--budget",
        type=float,
        default=float(os.environ.get("DEEQU_TPU_BENCH_BUDGET_S", "1200")),
        help="overall wall budget in seconds; secondary configs are "
        "skipped once the remainder can't cover their estimated cost "
        "(default: $DEEQU_TPU_BENCH_BUDGET_S or 1200)",
    )
    parser.add_argument(
        "--quick",
        action="store_true",
        help="headline profiler config only, at 1/8 scale",
    )
    parser.add_argument(
        "--configs",
        default="",
        help="comma-separated config names to run (e.g. "
        "'streaming_ingest_parallel'); skips the headline profiler "
        "unless 'profiler' is listed",
    )
    parser.add_argument(
        "--artifact",
        default="",
        help="also write the full detail JSON (the stderr document) "
        "to this path",
    )
    parser.add_argument(
        "--inline",
        action="store_true",
        default=os.environ.get("DEEQU_TPU_BENCH_INLINE", "0") == "1",
        help="run configs in-process instead of subprocess-per-config "
        "(debugging only: one SIGSEGV then kills the whole bench); "
        "also $DEEQU_TPU_BENCH_INLINE=1",
    )
    args = parser.parse_args(argv)
    wanted = {
        name.strip() for name in args.configs.split(",") if name.strip()
    }

    start = time.time()

    def remaining() -> float:
        return args.budget - (time.time() - start)

    # what can THIS host sustain? probe first, size everything from it
    host = probe_host()
    sizing = autosize(host)
    scale = sizing["row_scale"]
    print(
        f"[bench] host: {host.get('cpu_count')} cores, "
        f"{host.get('mem_available_mb')} MB available, "
        f"backend={host.get('jax_backend', '?')} "
        f"x{host.get('jax_device_count', '?')}; row scale {scale}"
        + (
            f", streamed rows capped at {sizing['streaming_row_cap']}"
            if sizing["streaming_row_cap"]
            else ""
        ),
        file=sys.stderr,
        flush=True,
    )

    # scaled to one chip: 4M rows x 20 cols for the headline profiler
    # run at scale 1.0, auto-sized down on small hosts
    prof_rows = _sized(500_000 if args.quick else 4_000_000, sizing)
    prof_cols = 20
    detail = {
        "budget_s": args.budget,
        "quick": args.quick,
        "isolated": not args.inline,
        "host": host,
        "sizing": sizing,
        "skipped": [],
        "config_status": {},
    }

    def run_one(name: str, cfg_args: dict, est_s: float) -> dict:
        """ONE config through a spawn-started child (crash isolation:
        a config that segfaults or stalls becomes a status entry, not
        the end of the bench). Fills detail[name] on success and
        detail["config_status"][name] always. A child CRASH (killed by
        a signal — usually the OOM killer on a small host) steps the
        config's row count down by halving and retries, so EVERY
        config yields a number somewhere on any host; the step-down
        trail rides the status entry (``row_step_downs``,
        ``rows_effective``)."""
        cfg_args = dict(cfg_args)
        status = {"rows": cfg_args.get("rows"), "estimated_s": est_s}
        t0 = time.time()
        step_downs: list = []
        while True:
            payload = {"name": name, "args": dict(cfg_args)}
            restore_env = _apply_child_env(name)
            try:
                if args.inline:
                    detail[name] = _bench_child(payload)
                else:
                    from deequ_tpu.engine.subproc import IsolatedRunner

                    runner = IsolatedRunner(
                        key=f"bench:{name}",
                        # bench configs are not checkpointer-resumable,
                        # so one crash = one failed attempt, no relaunch
                        max_relaunches=1,
                        use_breaker=False,
                        timeout_s=max(120.0, min(remaining(), est_s * 3.0)),
                    )
                    detail[name] = runner.run(_bench_child, payload)
                status["status"] = "ok"
                # a success after step-downs is a success — the trail
                # below documents the crashes that led here
                for key in ("error", "signal", "exitcode"):
                    status.pop(key, None)
                break
            except BaseException as exc:  # noqa: BLE001 — a status, never a crash
                sig = getattr(exc, "last_signal", None) or getattr(
                    exc, "signal_name", None
                )
                rc = getattr(exc, "last_exitcode", None)
                if rc is None:
                    rc = getattr(exc, "exitcode", None)
                if sig == "timeout":
                    status["status"] = "timeout"
                elif sig is not None or rc is not None:
                    status["status"] = "crashed"
                else:
                    status["status"] = "error"
                status["error"] = repr(exc)
                if sig is not None:
                    status["signal"] = sig
                if rc is not None:
                    status["exitcode"] = rc
                rows = cfg_args.get("rows")
                if (
                    status["status"] == "crashed"
                    and isinstance(rows, int)
                    and rows // 2 >= 100_000
                    and len(step_downs) < 3
                ):
                    cfg_args["rows"] = rows // 2
                    step_downs.append(cfg_args["rows"])
                    print(
                        f"[bench] {name} crashed at {rows} rows "
                        f"({sig or rc}); stepping down to "
                        f"{cfg_args['rows']}",
                        file=sys.stderr,
                        flush=True,
                    )
                    continue
                detail.setdefault("errors", {})[name] = repr(exc)
                break
            finally:
                restore_env()
        if step_downs:
            status["row_step_downs"] = step_downs
            status["rows_effective"] = cfg_args.get("rows")
        status["wall_s"] = round(time.time() - t0, 1)
        detail["config_status"][name] = status
        detail.setdefault("config_walls", {})[name] = status["wall_s"]
        return status

    if not wanted or "profiler" in wanted:
        st = run_one(
            "profiler", {"rows": prof_rows, "cols": prof_cols}, 300
        )
        if st["status"] != "ok":
            detail["error"] = st.get("error", "headline config failed")

    def headline_line() -> dict:
        prof = detail.get("profiler")
        if isinstance(prof, dict):
            rows_per_sec = prof["rows_per_sec"]
            return {
                "metric": "rows/sec/chip, full ColumnProfiler "
                f"({prof_rows}x{prof_cols} scaled TPC-DS-like)",
                "value": round(rows_per_sec, 1),
                "unit": "rows/sec/chip",
                "vs_baseline": round(
                    rows_per_sec / NORTH_STAR_ROWS_PER_SEC_PER_CHIP, 4
                ),
                # decomposition context: the tunneled chip's
                # host->device link swings 4-1400 MB/s between runs and
                # fresh-data walls are usually link-bound;
                # resident_rows_per_sec is the chip's compute/dispatch
                # capability with data in HBM (what a real pod reading
                # from local storage at GB/s would see)
                "link_mb_per_sec": round(prof["link_mb_per_sec"], 2),
                "resident_rows_per_sec": round(
                    prof["resident_rows_per_sec"], 1
                ),
            }
        return {  # headline config failed: the line still prints
            "metric": "rows/sec/chip, full ColumnProfiler "
            f"({prof_rows}x{prof_cols} scaled TPC-DS-like)",
            "value": 0.0,
            "unit": "rows/sec/chip",
            "vs_baseline": 0.0,
            "error": detail.get("error", "headline config failed"),
        }

    # print (and FLUSH) the headline line the moment it exists: if the
    # harness kills the process mid-secondary (rc=124), stdout still
    # carries a parseable result — the enriched final line below
    # supersedes it when the run finishes
    print(json.dumps({**headline_line(), "preliminary": True}), flush=True)
    print(
        f"[bench] headline done at {time.time() - start:.1f}s, "
        f"{remaining():.0f}s of budget left",
        file=sys.stderr,
        flush=True,
    )

    # (name, base args, streamed?, estimated cost in seconds at scale
    # 1.0) — the estimate is the gate: a config only starts when the
    # remaining budget covers it, so the overall wall stays under
    # --budget instead of rc=124-ing the harness (BENCH_r05). Rows are
    # auto-sized per host before launch; streamed configs additionally
    # respect the streaming row cap.
    # ORDER MATTERS (r6): the two wide-profiler configs run FIRST so
    # the cell-rate headline fields (ns_per_cell_50col,
    # projected_1b_x50_resident_8chip_s) exist even when the harness
    # rc=124-kills the process partway through the slower tail configs
    # — 4M x 50 is the round-over-round cell-rate headline, 8M x 50 is
    # the scaling check the <60 s north-star verdict reads
    secondary = (
        []
        if args.quick
        else [
            ("profiler_50col", {"rows": 4_000_000}, False, 150),
            ("profiler_50col_8m", {"rows": 8_000_000}, False, 200),
            ("fused_bundle_10col", {"rows": 8_000_000}, False, 60),
            ("grouping_5cat", {"rows": 4_000_000}, False, 60),
            ("one_pass_spill_grouping", {"rows": 4_000_000}, False, 100),
            ("sketches_hll_kll", {"rows": 8_000_000}, False, 60),
            ("resilience_overhead", {"rows": 4_000_000}, False, 90),
            ("memory_backoff_overhead", {"rows": 4_000_000}, False, 90),
            ("watchdog_overhead", {"rows": 4_000_000}, False, 90),
            (
                "service_concurrent_suites",
                {"rows": 2_000_000, "clients": 8},
                False,
                90,
            ),
            (
                "service_coalesced_suites",
                {"rows": 2_000_000, "clients": 4},
                False,
                120,
            ),
            (
                "service_elastic_placement",
                {"rows": 1_000_000, "clients": 4},
                False,
                120,
            ),
            (
                "service_preemption",
                {"rows": 1_000_000, "clients": 4},
                False,
                150,
            ),
            ("spill_grouping_12M_distinct", {"rows": 12_000_000}, False, 120),
            (
                "joint_grouping_mi_1Mcard_pair",
                {"rows": 4_000_000},
                False,
                120,
            ),
            # streaming ests = worst observed link (BENCH_r03 hit 386s
            # on a degraded tunnel), not the healthy-link median —
            # gating on the median is how r05 overran its budget
            ("streaming_parquet", {"rows": 4_000_000, "cols": 10}, True, 390),
            ("streaming_wire_diet", {"rows": 4_000_000}, True, 390),
            (
                "streaming_ingest_parallel",
                {"rows": 4_000_000, "cols": 10},
                True,
                400,
            ),
            ("streaming_bundle_100m", {"rows": 100_000_000}, True, 330),
            ("rowlevel_egress", {"rows": 4_000_000}, True, 200),
            ("egress_resume", {"rows": 800_000}, True, 150),
            ("fleet_failover", {"rows": 400_000}, False, 150),
        ]
    )

    def merge_wide(result: dict) -> dict:
        # the 50-col cell-rate headline (VERDICT r4) plus the r6 8M
        # scaling check: resident rate on the north-star-shaped config
        # and its link-independent projection — the one number to
        # compare round over round regardless of what the tunnel link
        # did during the run. The 8M x 50 run supersedes 4M x 50 for
        # the projection (amortizes per-step overhead the way a 1B run
        # would); 4M x 50 remains the comparable-cell-rate field.
        wide = detail.get("profiler_50col")
        if isinstance(wide, dict) and "resident_rows_per_sec" in wide:
            result["resident_rows_per_sec_50col"] = round(
                wide["resident_rows_per_sec"], 1
            )
            result["ns_per_cell_50col"] = round(wide["ns_per_cell"], 2)
            result["projected_1b_x50_resident_8chip_s"] = round(
                wide["projected_1b_x50_resident_8chip_s"], 1
            )
        wide8 = detail.get("profiler_50col_8m")
        if isinstance(wide8, dict) and "resident_rows_per_sec" in wide8:
            result["ns_per_cell_50col_8m"] = round(
                wide8["ns_per_cell"], 2
            )
            result["projected_1b_x50_resident_8chip_s"] = round(
                wide8["projected_1b_x50_resident_8chip_s"], 1
            )
        return result

    try:
        for name, base_args, streamed, est_s in secondary:
            if wanted and name not in wanted:
                continue
            # a scaled-down config finishes faster; the +20s covers the
            # child's own import+compile on top of the scaled run
            est_eff = (
                est_s
                if scale >= 1.0
                else max(45, int(est_s * scale) + 20)
            )
            if remaining() < est_eff:
                detail["skipped"].append(
                    {
                        "config": name,
                        "estimated_s": est_eff,
                        "remaining_s": round(remaining(), 1),
                    }
                )
                detail["config_status"][name] = {
                    "status": "skipped",
                    "estimated_s": est_eff,
                    "remaining_s": round(remaining(), 1),
                }
                print(
                    f"[bench] SKIPPED {name} (est {est_eff}s > "
                    f"{remaining():.0f}s remaining)",
                    file=sys.stderr,
                    flush=True,
                )
                continue
            cfg = dict(base_args)
            cfg["rows"] = _sized(base_args["rows"], sizing, streamed)
            print(
                f"[bench] running {name} ({cfg['rows']} rows)...",
                file=sys.stderr,
                flush=True,
            )
            st = run_one(name, cfg, est_eff)
            print(
                f"[bench] {name}: {st['status']} in {st['wall_s']}s "
                f"({remaining():.0f}s of budget left)",
                file=sys.stderr,
                flush=True,
            )
            if name in ("profiler_50col", "profiler_50col_8m"):
                # re-emit the preliminary line the moment a wide config
                # lands: the cell-rate/projection fields survive an
                # rc=124 kill during the remaining (slower) tail configs
                print(
                    json.dumps(
                        {**merge_wide(headline_line()), "preliminary": True}
                    ),
                    flush=True,
                )
    finally:
        # the artifact and the headline line ALWAYS emit, complete with
        # per-config status, whatever the configs did — partial results
        # with provenance beat a dead harness (rc stays 0)
        from deequ_tpu.telemetry import get_telemetry

        # the process-wide telemetry picture of everything the bench
        # ran: counter totals + the pass-latency histogram
        # (docs/OBSERVABILITY.md); children's counters/events were
        # merged in by IsolatedRunner as each config completed
        try:
            detail["telemetry"] = get_telemetry().metrics.snapshot()
        except Exception as exc:  # noqa: BLE001
            detail["telemetry_error"] = repr(exc)
        detail["total_wall_s"] = round(time.time() - start, 1)

        result = merge_wide(headline_line())
        print(json.dumps(detail, indent=2, default=str), file=sys.stderr)
        if args.artifact:
            try:
                with open(args.artifact, "w", encoding="utf-8") as fh:
                    json.dump(detail, fh, indent=2, default=str)
                    fh.write("\n")
            except OSError as exc:
                print(
                    f"[bench] artifact write failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )
        print(json.dumps(result, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
