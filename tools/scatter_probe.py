"""HLL register scatter-max experiments (round 5, VERDICT next #1).

The numeric-HLL scatter is the dominant term in the 1B x 50 compute
model (~145 M elem/s measured in r4 across every XLA formulation —
docs/PERF.md).  This probe measures Pallas kernel variants against the
XLA scatter on the REAL chip with the fetch-forced methodology PERF.md
prescribes (``jax.block_until_ready`` does not block on this backend):

- each timed sample runs K data-dependent repetitions of the op inside
  one jitted call (the register carry makes them sequential), then one
  scalar fetch forces completion; the ~100 ms tunnel round trip is
  amortized over K ops and subtracted via a null-op baseline.

Mosaic constraints discovered here (and encoded in the variants):
- BlockSpec index maps must return i32: under x64 (deequ_tpu enables
  it) a literal 0 traces as i64 and Mosaic fails to legalize the
  index-map func.return;
- scalar stores into VMEM refs are unsupported ("Cannot store scalars
  to VMEM") -> the register file lives in an SMEM output (64 KB);
- scalar LOADS from VMEM blocks are unsupported too -> inputs stream
  as SMEM blocks (small chunks, grid-pipelined DMA).

Run:  python tools/scatter_probe.py [--b 21] [--reps 8] [--iters 3]

Production-shape mode (``--prod``): the fused-scan shape the engine
actually dispatches — C=40 columns x B=2^21 rows x M=2^14 registers —
timed as the STACKED scatter (one flat XLA scatter-max, exactly
sketches/hll.registers_from_hash_pair_stacked's formulation) against
the wired (C, G)-grid Pallas kernel (sketches/pallas_scatter.py, the
same code ``config.pallas_scatter`` enables). Emits one
machine-parseable line prefixed ``PROD_JSON:`` so the flag's default
can be justified from an artifact instead of a doc table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # run from a source checkout without installing

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.sketches.hll import M, P

B_LOG2_DEFAULT = 21


def xla_scatter(regs, idx, rho):
    return jnp.maximum(regs, jnp.zeros(M, jnp.int32).at[idx].max(rho))


def make_pallas_two_stream(b_log2: int, chunk_log2: int, skip_cold: bool):
    """idx and rho as separate SMEM streams; registers in SMEM out."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = 1 << b_log2
    CHUNK = 1 << chunk_log2
    G = B // CHUNK

    def kernel(idx_ref, rho_ref, reg_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            def z(i, _):
                reg_ref[0, i] = 0
                return jnp.int32(0)

            jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(M), z, jnp.int32(0)
            )

        def body(i, _):
            r = idx_ref[0, i]
            v = rho_ref[0, i]
            cur = reg_ref[0, r]
            if skip_cold:
                @pl.when(v > cur)
                def _store():
                    reg_ref[0, r] = v
            else:
                reg_ref[0, r] = jnp.maximum(cur, v)
            return jnp.int32(0)

        jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(CHUNK), body, jnp.int32(0)
        )

    call = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(
                (1, CHUNK), lambda g: (jnp.int32(0), g), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(
                (1, CHUNK), lambda g: (jnp.int32(0), g), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, M), lambda g: (jnp.int32(0), jnp.int32(0)), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, M), jnp.int32),
    )

    def fn(regs, idx, rho):
        out = call(idx.reshape(1, B), rho.reshape(1, B))
        return jnp.maximum(regs, out.reshape(M))

    return fn


def make_pallas_packed(
    b_log2: int, chunk_log2: int, unroll: int, skip_cold: bool = True
):
    """ONE SMEM stream of (idx << 6 | rho) words: half the SMEM
    traffic and one scalar load per element; unpack with scalar
    shift/mask. ``unroll`` elements per fori iteration to cut loop
    bookkeeping."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = 1 << b_log2
    CHUNK = 1 << chunk_log2
    G = B // CHUNK

    def kernel(packed_ref, reg_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            def z(i, _):
                reg_ref[0, i] = 0
                return jnp.int32(0)

            jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(M), z, jnp.int32(0)
            )

        def body(i, _):
            base = i * jnp.int32(unroll)
            for u in range(unroll):
                w = packed_ref[0, base + u]
                r = jax.lax.shift_right_logical(w, jnp.int32(6))
                v = jnp.bitwise_and(w, jnp.int32(63))
                cur = reg_ref[0, r]

                if skip_cold:
                    @pl.when(v > cur)
                    def _store():
                        reg_ref[0, r] = v
                else:
                    reg_ref[0, r] = jnp.maximum(cur, v)

            return jnp.int32(0)

        jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(CHUNK // unroll), body, jnp.int32(0)
        )

    call = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(
                (1, CHUNK), lambda g: (jnp.int32(0), g), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, M), lambda g: (jnp.int32(0), jnp.int32(0)), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, M), jnp.int32),
    )

    def fn(regs, idx, rho):
        packed = jnp.bitwise_or(jnp.left_shift(idx, 6), rho)
        out = call(packed.reshape(1, B))
        return jnp.maximum(regs, out.reshape(M))

    return fn


def make_pallas_gmin(b_log2: int, chunk_log2: int, unroll: int):
    """The steady-state gate: registers carry IN (warm from previous
    batches), and the scalar min over them (gmin) lets every element
    with rho <= gmin skip the register load AND store — in steady
    state that is ~1 - 2^-gmin ~ 94% of elements doing only the packed
    load + one compare. gmin refreshes at every chunk boundary whose
    index is a multiple of 16 (cheap: M scalar reads amortized over
    16 * CHUNK elements)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = 1 << b_log2
    CHUNK = 1 << chunk_log2
    G = B // CHUNK

    def kernel(regs_in_ref, packed_ref, reg_ref, gmin_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            def cp(i, acc):
                w = regs_in_ref[0, i]
                reg_ref[0, i] = w
                return jnp.minimum(acc, w)

            g0 = jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(M), cp, jnp.int32(127)
            )
            gmin_ref[0] = g0

        @pl.when(
            jnp.logical_and(
                pl.program_id(0) > 0,
                jnp.bitwise_and(
                    pl.program_id(0), jnp.int32(15)
                ) == 0,
            )
        )
        def _refresh():
            def mn(i, acc):
                return jnp.minimum(acc, reg_ref[0, i])

            gmin_ref[0] = jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(M), mn, jnp.int32(127)
            )

        gmin = gmin_ref[0]

        def body(i, _):
            base = i * jnp.int32(unroll)
            for u in range(unroll):
                w = packed_ref[0, base + u]
                v = jnp.bitwise_and(w, jnp.int32(63))

                @pl.when(v > gmin)
                def _hot():
                    r = jax.lax.shift_right_logical(w, jnp.int32(6))
                    cur = reg_ref[0, r]

                    @pl.when(v > cur)
                    def _store():
                        reg_ref[0, r] = v

            return jnp.int32(0)

        jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(CHUNK // unroll), body, jnp.int32(0)
        )

    call = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(
                (1, M),
                lambda g: (jnp.int32(0), jnp.int32(0)),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(
                (1, CHUNK), lambda g: (jnp.int32(0), g),
                memory_space=pltpu.SMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, M),
            lambda g: (jnp.int32(0), jnp.int32(0)),
            memory_space=pltpu.SMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((1, M), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
    )

    def fn(regs, idx, rho):
        packed = jnp.bitwise_or(jnp.left_shift(idx, 6), rho)
        out = call(regs.reshape(1, M), packed.reshape(1, B))
        return out.reshape(M)

    return fn


def chained(fn, reps):
    """K data-dependent applications per dispatch: the carry makes the
    ops sequential so wall ~= K * op + one round trip."""

    @jax.jit
    def run(regs, idx, rho):
        def step(k, acc):
            # vary the input per step so XLA cannot CSE the chain:
            # rotate indices by a step-dependent offset (stays in
            # [0,M)); keep everything i32 — an int64 input stream
            # breaks the SMEM kernels (x64 is on)
            i2 = jnp.bitwise_and(
                idx + k.astype(jnp.int32), jnp.int32(M - 1)
            )
            return fn(acc, i2, rho)

        return jax.lax.fori_loop(0, reps, step, regs)

    return run


def fetch_forced(run, args, iters):
    out = run(*args)
    _ = int(jnp.max(out))  # warm: compile + first exec
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = run(*args)
        _ = int(jnp.max(out))
        samples.append(time.perf_counter() - t0)
    return min(samples)


def xla_scatter_stacked(regs, idx, rho):
    """(C, B) -> (C, M) via the flat stacked scatter-max — the exact
    XLA formulation of hll.registers_from_hash_pair_stacked."""
    n_cols = idx.shape[0]
    col_ids = jax.lax.broadcasted_iota(jnp.int32, idx.shape, 0)
    flat = (col_ids * M + idx).ravel()
    return jnp.maximum(
        regs,
        jnp.zeros(n_cols * M, jnp.int32)
        .at[flat]
        .max(rho.ravel())
        .reshape(n_cols, M),
    )


def make_pallas_stacked():
    """The PRODUCTION kernel: sketches/pallas_scatter's (C, G)-grid
    unroll-16 packed variant — what config.pallas_scatter wires in."""
    from deequ_tpu.sketches import pallas_scatter as ps

    def fn(regs, idx, rho):
        out = ps._scatter_max_call(idx, rho, M, ps._interpret_forced())
        return jnp.maximum(regs, out)

    return fn


def prod_mode(args) -> None:
    """C=40 x B=2^b x M production shape; prints a PROD_JSON line."""
    C, B = args.cols, 1 << args.b
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, M, (C, B), dtype=np.int32))
    rho = jnp.asarray(
        np.minimum(rng.geometric(0.5, (C, B)).astype(np.int32), 33)
    )
    regs0 = jnp.zeros((C, M), jnp.int32)
    idx_same = jnp.zeros((C, B), jnp.int32)

    print(f"prod shape: C={C}, B=2^{args.b}, M={M}, reps={args.reps}")
    null = chained(lambda r, i, v: jnp.maximum(r, 0), args.reps)
    rt = fetch_forced(null, (regs0, idx, rho), args.iters)
    print(f"round-trip baseline: {rt * 1e3:.1f} ms")

    record = {
        "mode": "prod",
        "C": C,
        "b_log2": args.b,
        "M": M,
        "reps": args.reps,
        "backend": jax.default_backend(),
        "roundtrip_ms": rt * 1e3,
        "variants": {},
    }
    want = want_same = None
    for name, fn in (
        ("xla_stacked", xla_scatter_stacked),
        ("pallas_stacked_u16", make_pallas_stacked()),
    ):
        try:
            run = chained(fn, args.reps)
            got = np.asarray(run(regs0, idx, rho))
            got_same = np.asarray(run(regs0, idx_same, rho))
            if want is None:
                want, want_same = got, got_same
                ok = True
            else:
                ok = bool(
                    (got == want).all() and (got_same == want_same).all()
                )
            wall = fetch_forced(run, (regs0, idx, rho), args.iters) - rt
            per_op = wall / args.reps
            rate = C * B / per_op / 1e6
            record["variants"][name] = {
                "bit_identical": ok,
                "per_op_ms": per_op * 1e3,
                "m_elem_per_s": rate,
            }
            print(
                f"{name:>24}: {per_op * 1e3:8.2f} ms/op  "
                f"{rate:8.1f} M elem/s  "
                f"[{'OK' if ok else 'WRONG'}]"
            )
        except Exception as e:  # noqa: BLE001 — probe tool
            msg = str(e).splitlines()[0][:160]
            record["variants"][name] = {"error": msg}
            print(f"{name:>24}: FAILED {type(e).__name__}: {msg}")
    xla = record["variants"].get("xla_stacked", {})
    pallas = record["variants"].get("pallas_stacked_u16", {})
    if "per_op_ms" in xla and "per_op_ms" in pallas:
        record["pallas_speedup"] = xla["per_op_ms"] / pallas["per_op_ms"]
    print("PROD_JSON: " + json.dumps(record))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=B_LOG2_DEFAULT)
    ap.add_argument("--reps", type=int, default=8)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--chunks", type=str, default="11,13")
    ap.add_argument(
        "--prod",
        action="store_true",
        help="production-shape stacked probe (C x 2^b x M) + JSON line",
    )
    ap.add_argument("--cols", type=int, default=40)
    args = ap.parse_args()

    if args.prod:
        prod_mode(args)
        return

    B = 1 << args.b
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, M, B, dtype=np.int32))
    rho = jnp.asarray(
        np.minimum(
            rng.geometric(0.5, B).astype(np.int32), 33
        )  # real HLL rank distribution: P(rho=k) = 2^-k from k=1
    )
    regs0 = jnp.zeros(M, jnp.int32)
    # adversarial collision input: every element hits ONE register —
    # correctness under maximal aliasing (ordering hazards show here)
    idx_same = jnp.zeros(B, jnp.int32)

    print(f"B=2^{args.b}, M={M} (P={P}), reps={args.reps}")

    null = chained(lambda r, i, v: jnp.maximum(r, 0), args.reps)
    rt = fetch_forced(null, (regs0, idx, rho), args.iters)
    print(f"round-trip baseline: {rt * 1e3:.1f} ms")

    variants = [("xla_scatter", xla_scatter)]
    for chunk in (int(c) for c in args.chunks.split(",")):
        variants.append(
            (f"two_stream_c{chunk}",
             make_pallas_two_stream(args.b, chunk, skip_cold=False))
        )
        variants.append(
            (f"two_stream_skip_c{chunk}",
             make_pallas_two_stream(args.b, chunk, skip_cold=True))
        )
        for unroll in (4, 8, 16):
            variants.append(
                (f"packed_c{chunk}_u{unroll}",
                 make_pallas_packed(args.b, chunk, unroll))
            )
        variants.append(
            (f"packed_c{chunk}_u8_nosk",
             make_pallas_packed(args.b, chunk, 8, skip_cold=False))
        )
        for unroll in (8, 16):
            variants.append(
                (f"gmin_c{chunk}_u{unroll}",
                 make_pallas_gmin(args.b, chunk, unroll))
            )

    want = want_same = None
    for name, fn in variants:
        try:
            run = chained(fn, args.reps)
            got = np.asarray(run(regs0, idx, rho))
            got_same = np.asarray(run(regs0, idx_same, rho))
            if want is None:
                want, want_same = got, got_same
                ok = "ref"
            else:
                ok = (
                    "OK"
                    if (got == want).all() and (got_same == want_same).all()
                    else "WRONG"
                )
            wall = fetch_forced(run, (regs0, idx, rho), args.iters) - rt
            per_op = wall / args.reps
            rate = B / per_op / 1e6
            print(
                f"{name:>24}: {per_op * 1e3:7.2f} ms/op  "
                f"{rate:8.1f} M elem/s  [{ok}]"
            )
        except Exception as e:  # noqa: BLE001 — probe tool
            msg = str(e).splitlines()[0][:120]
            print(f"{name:>24}: FAILED {type(e).__name__}: {msg}")


if __name__ == "__main__":
    main()
