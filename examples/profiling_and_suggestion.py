"""Column profiling and automatic constraint suggestion.

Reference examples: data-profiling + constraint-suggestion examples
(SURVEY.md §2.5, §3.3, §3.4): profile every column in a few fused
passes, then derive candidate constraints from the profiles and verify
them on a holdout split.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # allow running from a source checkout without installing

import numpy as np

from deequ_tpu import (
    DEFAULT_RULES,
    ColumnProfilerRunner,
    ConstraintSuggestionRunner,
    Dataset,
)


def main():
    rng = np.random.default_rng(3)
    n = 50_000
    data = Dataset.from_pydict(
        {
            "order_id": np.arange(n),
            "status": rng.choice(["open", "shipped", "done"], n),
            "amount": np.abs(rng.normal(80.0, 30.0, n)),
            "discount_code": [
                None if i % 5 else f"D{i % 7}" for i in range(n)
            ],
            "qty_as_string": [str(int(q)) for q in rng.integers(1, 9, n)],
        }
    )

    profiles = ColumnProfilerRunner().on_data(data).run()
    print(f"profiled {len(profiles.profiles)} columns, "
          f"{profiles.num_records} rows")
    for name, profile in profiles.profiles.items():
        print(f"  {name}: type={profile.data_type.value} "
              f"completeness={profile.completeness:.2f} "
              f"approx_distinct={profile.approximate_num_distinct_values:.0f}")
    if profiles.run_metadata:
        for rec in profiles.run_metadata.as_records():
            print(f"  [pass {rec['pass']}] {rec['wall_s']:.2f}s "
                  f"({rec['rows_per_sec']:.0f} rows/s)")

    result = (
        ConstraintSuggestionRunner()
        .on_data(data)
        .add_constraint_rules(DEFAULT_RULES)
        .use_train_test_split_with_testset_ratio(0.2)
        .run()
    )
    print("suggested constraints (verified on a 20% holdout):")
    for suggestion in result.all_suggestions():
        print(f"  {suggestion.constraint_description}: "
              f"{suggestion.code_for_constraint}")
    if result.verification_result is not None:
        print(f"holdout verification: {result.verification_result.status}")


if __name__ == "__main__":
    main()
