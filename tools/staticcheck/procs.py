"""Subprocess-discipline analyzer: every child process in the product
tree must come from the one module that owns the process lifecycle.

PR 11's crash isolation multiplied the number of PROCESSES the engine
may run at once, and its contracts — children always reaped (the
no-zombie assertion in tier-1), children always spawn-started (a forked
JAX child inherits locked allocator/backend state and deadlocks or
corrupts; see docs/RESILIENCE.md) — only hold if process creation is
centralized. One rule, three checks:

``subprocess-discipline``

1. **Sanctioned modules** — ``multiprocessing`` / ``subprocess`` /
   ``concurrent.futures.ProcessPoolExecutor`` may only be imported in
   the modules that own a documented child lifecycle (today:
   ``engine/subproc.py``). A child spawned from an analyzer or a codec
   has no owner to reap it and no crash classification.
2. **Spawn, never fork** — ``os.fork``/``forkpty``/``posix_spawn`` are
   flagged everywhere, and inside sanctioned modules
   ``multiprocessing.get_context`` must be called with ``"spawn"``;
   constructing ``multiprocessing.Process`` directly (platform default
   = fork on Linux) is flagged too.
3. **Reaped, never zombied** — a process object that is ``.start()``ed
   in a sanctioned module must also be ``.join()``ed somewhere in that
   module (the ``finally``-block reap in ``IsolatedRunner``); a started
   child nobody joins becomes a zombie holding its exit status.

Waive with ``# lint-ok: subprocess-discipline: <reason>`` where a site
carries its own documented lifecycle.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

#: modules with a documented child-process lifecycle (spawn + reap)
SANCTIONED = frozenset(
    {
        "deequ_tpu/engine/subproc.py",
    }
)

#: top-level modules whose import means "this file makes processes"
PROCESS_MODULES = frozenset({"multiprocessing", "subprocess"})

#: fork-family calls: never legal in the product tree — a forked JAX
#: child shares the parent's backend/allocator state mid-mutation
FORK_CALLS = frozenset(
    {
        "os.fork",
        "os.forkpty",
        "os.posix_spawn",
        "os.posix_spawnp",
        "pty.fork",
    }
)


def _call_tail(callee: str) -> str:
    return callee.split(".")[-1]


def _from_imports(tree: ast.AST, module: str) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            names.update(alias.asname or alias.name for alias in node.names)
    return names


class SubprocessDisciplineAnalyzer(Analyzer):
    name = "procs"
    rules = ("subprocess-discipline",)
    description = (
        "child processes only in sanctioned modules, spawn-started "
        "(never forked), and always joined/reaped"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if sf.tree is None or not sf.rel.startswith("deequ_tpu/"):
                continue
            yield from self._analyze_file(sf)

    # -- per-file ---------------------------------------------------------

    def _analyze_file(self, sf: SourceFile) -> Iterable[Finding]:
        sanctioned = sf.rel in SANCTIONED
        mp_names = _from_imports(sf.tree, "multiprocessing")

        yield from self._check_imports(sf, sanctioned)
        yield from self._check_calls(sf, sanctioned, mp_names)
        if sanctioned:
            yield from self._check_reaping(sf)

    def _check_imports(
        self, sf: SourceFile, sanctioned: bool
    ) -> Iterable[Finding]:
        if sanctioned:
            return
        for node in ast.walk(sf.tree):
            modules: List[str] = []
            if isinstance(node, ast.Import):
                modules = [alias.name.split(".")[0] for alias in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                top = node.module.split(".")[0]
                modules = [top]
                if node.module.startswith("concurrent"):
                    # from concurrent.futures import ProcessPoolExecutor
                    if any(
                        alias.name == "ProcessPoolExecutor"
                        for alias in node.names
                    ):
                        modules = ["multiprocessing"]
                    else:
                        modules = []
            else:
                continue
            for top in modules:
                if top in PROCESS_MODULES:
                    yield Finding(
                        rule="subprocess-discipline",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"{top} imported outside the sanctioned "
                            "process modules — a child spawned here has "
                            "no owner to reap it and no crash "
                            "classification; route the work through "
                            "engine/subproc.py (IsolatedRunner), or "
                            "waive with the lifecycle that reaps it"
                        ),
                        symbol=top,
                    )

    def _check_calls(
        self, sf: SourceFile, sanctioned: bool, mp_names: Set[str]
    ) -> Iterable[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func) or ""
            if callee in FORK_CALLS:
                yield Finding(
                    rule="subprocess-discipline",
                    path=sf.rel,
                    line=node.lineno,
                    message=(
                        f"{callee} forks the interpreter — a forked "
                        "JAX child inherits locked allocator/backend "
                        "state; use a spawn context via "
                        "engine/subproc.py instead"
                    ),
                    symbol=_call_tail(callee),
                )
                continue
            if not sanctioned:
                continue
            tail = _call_tail(callee)
            is_mp_attr = callee.startswith("multiprocessing.")
            is_mp_name = len(callee.split(".")) == 1 and tail in mp_names
            if tail == "get_context" and (is_mp_attr or is_mp_name):
                method = None
                if node.args and isinstance(node.args[0], ast.Constant):
                    method = node.args[0].value
                elif node.args:
                    method = "<dynamic>"
                if method != "spawn":
                    yield Finding(
                        rule="subprocess-discipline",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            "multiprocessing context must be "
                            "get_context('spawn') — the platform "
                            "default (fork on Linux) deadlocks "
                            "children that inherit JAX state; got "
                            f"{method!r}"
                        ),
                        symbol="get_context",
                    )
            elif tail in ("Process", "Pool") and (is_mp_attr or is_mp_name):
                yield Finding(
                    rule="subprocess-discipline",
                    path=sf.rel,
                    line=node.lineno,
                    message=(
                        f"bare multiprocessing.{tail} uses the "
                        "platform-default start method (fork on "
                        "Linux); construct via "
                        "get_context('spawn').{0}".format(tail)
                    ),
                    symbol=tail,
                )

    def _check_reaping(self, sf: SourceFile) -> Iterable[Finding]:
        """Every name assigned from a ``*.Process(...)`` construction
        that is ``.start()``ed must also be ``.join()``ed in this
        module — the reap that prevents zombies."""
        process_names: Set[str] = set()
        started: Dict[str, int] = {}
        joined: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = dotted_name(node.targets[0])
                value = node.value
                if (
                    target is not None
                    and isinstance(value, ast.Call)
                    and _call_tail(dotted_name(value.func) or "")
                    == "Process"
                ):
                    process_names.add(target)
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                receiver = dotted_name(node.func.value)
                if receiver is None:
                    continue
                if node.func.attr == "start":
                    started.setdefault(receiver, node.lineno)
                elif node.func.attr in ("join", "kill", "terminate"):
                    if node.func.attr == "join":
                        joined.add(receiver)
        for name, line in sorted(started.items(), key=lambda kv: kv[1]):
            if name not in process_names:
                continue  # not a Process (a thread, a timer, ...)
            if name not in joined:
                yield Finding(
                    rule="subprocess-discipline",
                    path=sf.rel,
                    line=line,
                    message=(
                        f"process {name!r} is started but never "
                        "joined in this module — an unreaped child "
                        "becomes a zombie holding its exit status; "
                        "join it in a finally block"
                    ),
                    symbol=name,
                )


register(SubprocessDisciplineAnalyzer())
