"""Randomized differential soak: resident+device-grouping vs
streaming+host-grouping over 200 random (dataset, analyzer-set) pairs —
random dtypes, null patterns, batch sizes. Histogram comparison is
tie-aware (top-K bins break count ties arbitrarily). Not part of the CI
suite (minutes of wall time); run manually before a release:

    python tools/soak_differential.py

Last run (round 5): 0 failures over 200 seeds (post sorted-dedup HLL,
dense-domain grouping, and predicate-grammar extensions).
"""

import sys, traceback
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from deequ_tpu import Dataset, config
from deequ_tpu.analyzers import (
    AnalysisRunner, ApproxCountDistinct, Completeness, Compliance,
    Correlation, CountDistinct, DataType, Distinctness, Entropy,
    Histogram, Maximum, MaxLength, Mean, Minimum, MinLength,
    PatternMatch, Size, StandardDeviation, Sum, Uniqueness,
    UniqueValueRatio,
)

def make_dataset(rng, n):
    cols = {}
    kinds = {}
    for i in range(rng.integers(2, 6)):
        kind = rng.choice(["f64", "f32", "i64", "i32", "str", "bool"])
        name = f"c{i}_{kind}"
        if kind in ("f64", "f32"):
            v = rng.normal(0, 10, n).astype(np.float32 if kind == "f32" else np.float64).astype(object)
        elif kind in ("i64", "i32"):
            v = rng.integers(-1000, 10_000, n).astype(object)
        elif kind == "bool":
            v = (rng.integers(0, 2, n) == 1).astype(object)
        else:
            v = np.array(["aa", "b", "ccc", "dd", "", "zz9"])[rng.integers(0, 6, n)].astype(object)
        if rng.random() < 0.6:
            v[:: int(rng.integers(3, 30))] = None
        cols[name] = list(v)
        kinds[name] = kind
    return Dataset.from_pydict(cols), kinds

def analyzers_for(rng, kinds):
    out = [Size()]
    for c, k in kinds.items():
        out.append(Completeness(c))
        if k in ("f64", "f32", "i64", "i32", "bool"):
            out += [Mean(c), Minimum(c), Maximum(c), Sum(c), StandardDeviation(c)]
        if k == "str":
            out += [MinLength(c), MaxLength(c), DataType(c), PatternMatch(c, r"^[a-z]+$")]
        if rng.random() < 0.7:
            out += [CountDistinct(c), Uniqueness(c), Distinctness(c)]
        if rng.random() < 0.4:
            out += [Entropy(c), UniqueValueRatio(c), Histogram(c)]
        out.append(ApproxCountDistinct(c))
    return out

fails = 0
for seed in range(200):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 30_000))
    try:
        ds, kinds = make_dataset(rng, n)
        an = analyzers_for(rng, kinds)
        ctx_a = AnalysisRunner.do_analysis_run(ds, an)
        with config.configure(device_cache_bytes=0, batch_size=int(rng.integers(256, 8192)), device_spill_grouping=False):
            ds2 = Dataset.from_arrow(ds.table)
            ctx_b = AnalysisRunner.do_analysis_run(ds2, an)
        for a in an:
            va, vb = ctx_a.metric(a).value, ctx_b.metric(a).value
            if va.is_success != vb.is_success:
                print(f"seed {seed}: success mismatch {a}: {va} vs {vb}", flush=True); fails += 1; continue
            if not va.is_success:
                continue
            x, y = va.get(), vb.get()
            if isinstance(x, float):
                if not (abs(x - y) <= 1e-8 * max(1.0, abs(x)) or (np.isnan(x) and np.isnan(y))):
                    print(f"seed {seed}: value mismatch {a}: {x} vs {y}", flush=True); fails += 1
            else:
                gx = getattr(x, "values", None); gy = getattr(y, "values", None)
                if gx is not None:
                    # top-K bins tie-break arbitrarily among equal counts:
                    # compare the count multiset + all common keys exactly
                    ok = sorted(v.absolute for v in gx.values()) == sorted(v.absolute for v in gy.values())
                    ok = ok and getattr(x, "number_of_bins", None) == getattr(y, "number_of_bins", None)
                    ok = ok and all(gx[k].absolute == gy[k].absolute for k in set(gx) & set(gy))
                    if not ok:
                        print(f"seed {seed}: dist mismatch {a}", flush=True); fails += 1
                elif str(x) != str(y):
                    print(f"seed {seed}: repr mismatch {a}: {x} vs {y}", flush=True); fails += 1
    except Exception:
        print(f"seed {seed}: EXCEPTION", flush=True)
        traceback.print_exc()
        fails += 1
    if seed % 20 == 19:
        print(f"... {seed+1} seeds done, {fails} failures", flush=True)
print(f"SOAK DONE: {fails} failures over 200 seeds", flush=True)
