"""``shard_map`` import/kwarg compatibility.

jax moved ``shard_map`` from ``jax.experimental.shard_map`` to the
top-level ``jax.shard_map`` and renamed the replication-check kwarg
``check_rep`` -> ``check_vma`` along the way. Call sites in this repo
use the new spelling; this shim resolves whichever location the
installed jax provides and translates the kwarg, so the sharded spill
and sketch-merge paths work on both old and new builds.
"""

from __future__ import annotations

import inspect

try:  # the long-standing location
    from jax.experimental.shard_map import shard_map as _shard_map
except ImportError:  # newer jax: promoted to the top level
    from jax import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
if "check_vma" in _PARAMS:
    _CHECK_KWARG = "check_vma"
elif "check_rep" in _PARAMS:
    _CHECK_KWARG = "check_rep"
else:
    _CHECK_KWARG = None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    kwargs = {}
    if _CHECK_KWARG is not None:
        kwargs[_CHECK_KWARG] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
