"""A 2-process distributed verification service over loopback: the
elastic-placement service driving the process-sharded global-array
feed (docs/SERVICE.md "Elastic placement" + docs/MULTIHOST.md).

Two real processes (4 virtual CPU devices each) initialize
``jax.distributed`` against a loopback coordinator. EACH process runs
an identical ``VerificationService`` replica — one worker, the same
submissions made before ``start()`` — so the run order is
deterministic and both processes execute the same collective scans in
the same order: the standard multi-controller SPMD discipline.
Process 0's queue IS the fleet's run queue; its peer merely mirrors
it. Every run leases the full 8-device global mesh from the elastic
placer, and the streaming scan's process-sharded ingest
(``engine/ingest.process_sharded_feed``) means each process reads
ONLY its own parquet row-group shard and contributes its local rows
to ONE global array per batch leaf via
``jax.make_array_from_process_local_data`` — no host ever sees the
other's rows.

The parent then recomputes the same suite over the WHOLE table in a
single process and asserts the fleet's metrics match.

    python examples/distributed_service.py

``--failover`` runs the OTHER distributed story instead — fleet
failover (docs/SERVICE.md "Fleet failover"): two plain service
replicas share a fleet directory, the victim is SIGKILLed at 50%
queue progress, and the survivor's heartbeat watch adopts its
journal, resumes the mid-flight run from the shared durable
checkpoint cursor, and finishes the backlog. That mode needs no
cross-process collectives and runs on plain CPU:

    python examples/distributed_service.py --failover

NOTE: like examples/multihost_grouping.py, the cross-process
collective scan needs a real multi-host backend; under
``JAX_PLATFORMS=cpu`` the CPU backend has no cross-host collective
transport, so tests/test_multihost.py carries this as a backend-keyed
xfail (it runs for real on a multi-host TPU slice).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_ROWS = 400_000
N_SUITES = 3

# the suite every tenant submits — shared source so the parent's
# whole-table reference run builds EXACTLY the same checks
SUITE_SRC = """
def make_suite(i):
    from deequ_tpu import Check, CheckLevel

    return [
        Check(CheckLevel.ERROR, f"fleet-suite-{i}")
        .is_complete("k1")
        .is_non_negative("k1")
        .is_complete("v1")
    ]
"""

WORKER = r"""
import json, sys
coordinator, pid, data_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
import os
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()

from deequ_tpu import Dataset
from deequ_tpu.service import (
    DevicePool,
    ElasticPlacer,
    PlacementPolicy,
    Priority,
    RunRequest,
    VerificationService,
)

_SUITE_SRC

ndev = len(jax.devices())  # 8 global devices, 4 addressable per host

# identical service replica on every process: ONE worker and all
# submissions made before start() make the pop order deterministic
# FIFO, so both replicas execute the same collective scans in the
# same order (multi-controller SPMD: replicate the controller, never
# fork it). The placer's policy pins every lease to the full global
# pool — the whole-mesh placement the sharded feed needs.
placer = ElasticPlacer(
    pool=DevicePool(jax.devices()),
    policy=PlacementPolicy(bytes_per_device=1, default_devices=ndev),
)
svc = VerificationService(
    workers=1, isolated=False, coalesce=False, placer=placer
)
handles = [
    svc.submit(
        RunRequest(
            tenant=f"tenant-{i}",
            checks=make_suite(i),
            dataset_key="fleet/shared-table",
            dataset_factory=lambda: Dataset.from_parquet(data_path),
            priority=Priority.BATCH,
        )
    )
    for i in range(N_SUITES)
]
svc.start()
try:
    results = [h.result(timeout=300) for h in handles]
finally:
    svc.stop(drain=False, timeout=30)

def _metric_value(m):
    try:
        return m.value.get()
    except Exception:  # noqa: BLE001 — a failed metric reports as text
        return str(getattr(m, "value", m))

out = {"placements": [], "runs": []}
for h, r in zip(handles, results):
    out["placements"].append(dict(h.placement or {}))
    out["runs"].append(
        {
            "status": str(r.status),
            "degradation": str(getattr(r, "degradation", None)),
            "metrics": {
                str(a): _metric_value(m)
                for a, m in dict(r.metrics).items()
            },
        }
    )
if pid == 0:
    print("SERVICE_METRICS " + json.dumps(out, default=str), flush=True)
print(f"worker {pid} done", flush=True)
""".replace("_SUITE_SRC", SUITE_SRC).replace("N_SUITES", str(N_SUITES))


#: the fleet-failover demo's victim replica: a whole service process —
#: heartbeat lease, journaled runs, shared-dir checkpoints — that the
#: parent SIGKILLs mid-queue (docs/SERVICE.md "Fleet failover"). Runs
#: on any backend, including plain CPU: failover needs only the shared
#: fleet directory, not cross-process collectives.
FAILOVER_VICTIM = r"""
import sys
fleet_dir, journal_dir, rows, n_runs = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
)
import numpy as np
from deequ_tpu import Check, CheckLevel, Dataset, config
from deequ_tpu.service import Priority, RunRequest, VerificationService

rng = np.random.default_rng(17)
data = {"a": rng.normal(size=rows).tolist()}
checks = [
    Check(CheckLevel.ERROR, "failover").has_size(lambda s: s == rows)
    .is_complete("a")
]
with config.configure(
    checkpoint_every_batches=4, batch_size=max(4096, rows // 32),
    device_cache_bytes=0,
    service_fleet_heartbeat_s=0.3, service_fleet_lease_timeout_s=1.2,
):
    svc = VerificationService(
        workers=1, isolated=False, journal_dir=journal_dir,
        fleet_dir=fleet_dir, replica_id="replica-victim",
    ).start()
    handles = [
        svc.submit(RunRequest(
            tenant="demo", checks=checks,
            dataset_key=f"demo-{i}",
            dataset_factory=lambda: Dataset.from_pydict(data),
            priority=Priority.STANDARD,
        ))
        for i in range(n_runs)
    ]
    for i, h in enumerate(handles):
        h.wait(timeout=600)
        print(f"DONE {i}", flush=True)
"""


def _run_failover(workdir: str, rows: int = 200_000, n_runs: int = 4):
    """Fleet failover over loopback: SIGKILL a replica at 50% queue
    progress; the survivor adopts its journal off the shared fleet dir
    and finishes the backlog, resuming the mid-flight run from its
    durable checkpoint cursor."""
    import signal
    import time

    import numpy as np

    from deequ_tpu import Check, CheckLevel, Dataset, config
    from deequ_tpu.service import RunRequest, RunState, VerificationService

    fleet_dir = os.path.join(workdir, "fleet")
    victim_journal = os.path.join(workdir, "victim-journal")
    survivor_journal = os.path.join(workdir, "survivor-journal")
    rng = np.random.default_rng(17)  # the victim builds the SAME table
    data = {"a": rng.normal(size=rows).tolist()}
    checks = [
        Check(CheckLevel.ERROR, "failover")
        .has_size(lambda s, rows=rows: s == rows)
        .is_complete("a")
    ]

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [
            sys.executable, "-c", FAILOVER_VICTIM,
            fleet_dir, victim_journal, str(rows), str(n_runs),
        ],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    try:
        for line in proc.stdout:
            print(f"victim: {line.strip()}", flush=True)
            if line.strip() == f"DONE {n_runs // 2 - 1}":
                os.kill(proc.pid, signal.SIGKILL)  # mid-queue, no warning
                break
    finally:
        if proc.poll() is None and proc.returncode is None:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        proc.stdout.close()
    print("victim SIGKILLed at 50% queue progress", flush=True)

    with config.configure(
        checkpoint_every_batches=4, batch_size=max(4096, rows // 32),
        device_cache_bytes=0,
        service_fleet_heartbeat_s=0.3, service_fleet_lease_timeout_s=1.2,
    ):
        svc = VerificationService(
            workers=1, isolated=False, journal_dir=survivor_journal,
            fleet_dir=fleet_dir, replica_id="replica-survivor",
            adopt_resolve=lambda entry: RunRequest(
                tenant=entry["tenant"],
                checks=checks,
                dataset_key=entry.get("dataset_key"),
                dataset_factory=lambda: Dataset.from_pydict(data),
            ),
        ).start()
        try:
            deadline = time.monotonic() + 30
            while not svc.adopted_runs() and time.monotonic() < deadline:
                time.sleep(0.1)  # the supervisor thread polls for us
            adopted = svc.adopted_runs()
            assert adopted, "survivor never adopted the victim's journal"
            snap = svc.health()["fleet"]
            print(
                f"survivor adopted {len(adopted)} run(s) from "
                f"{snap['adoptions'][0]['replica']} after "
                f"{snap['adoptions'][0]['stale_for_s']}s stale",
                flush=True,
            )
            for h in adopted:
                assert h.wait(timeout=300), h.run_id
                assert h.status == RunState.DONE, (h.run_id, h.status)
                print(
                    f"adopted {h.run_id}: {h.result(timeout=0).status}",
                    flush=True,
                )
        finally:
            svc.stop(drain=False, timeout=30)
    print(
        f"fleet failover (loopback, shared fleet dir): {len(adopted)} "
        "orphan run(s) adopted and finished, zero lost",
        flush=True,
    )


def main(argv=None) -> None:
    import shutil

    argv = sys.argv[1:] if argv is None else argv
    workdir = tempfile.mkdtemp(prefix="deequ_tpu_dist_svc_")
    try:
        if "--failover" in argv:
            _run_failover(workdir)
        else:
            _run(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _make_table():
    import numpy as np
    import pyarrow as pa

    rng = np.random.default_rng(17)
    k1 = rng.integers(0, 1 << 30, N_ROWS, dtype=np.int64)
    v1 = rng.normal(0, 1, N_ROWS).astype(np.float32).astype(object)
    v1[::13] = None  # completeness must see real nulls
    return pa.table(
        {"k1": k1, "v1": pa.array(list(v1), pa.float32())}
    )


def _run(workdir: str) -> None:
    import pyarrow.parquet as pq

    table = _make_table()
    # UNEQUAL multi-file shards so the row-group shard planner has
    # real work: each process's shard_view gets its own file(s)
    data_dir = os.path.join(workdir, "table")
    os.makedirs(data_dir, exist_ok=True)
    split = int(N_ROWS * 0.6)
    pq.write_table(
        table.slice(0, split), os.path.join(data_dir, "part0.parquet")
    )
    pq.write_table(
        table.slice(split), os.path.join(data_dir, "part1.parquet")
    )

    with socket.socket() as s:  # free loopback port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, coordinator, str(i), data_dir],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        for i in range(2)
    ]
    # shared deadline: when one worker dies its sibling hangs in the
    # collectives — kill it and report the real failure's output
    import time as _time

    deadline = _time.monotonic() + 600
    outputs = [b"", b""]
    try:
        for i, p in enumerate(procs):
            try:
                outputs[i], _ = p.communicate(
                    timeout=max(1.0, deadline - _time.monotonic())
                )
            except subprocess.TimeoutExpired:
                pass  # judged below after every worker is reaped
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for i, p in enumerate(procs):
            if p.poll() is None or not outputs[i]:
                try:
                    extra, _ = p.communicate(timeout=10)
                    outputs[i] = outputs[i] + (extra or b"")
                except Exception:  # noqa: BLE001 — reporting only
                    pass
    failed = [i for i, p in enumerate(procs) if p.returncode != 0]
    if failed:
        report = "\n".join(
            f"--- worker {i} (rc={procs[i].returncode}) ---\n"
            + outputs[i].decode(errors="replace")
            for i in range(2)
        )
        raise RuntimeError(f"worker(s) {failed} failed:\n{report}")

    got = None
    for line in outputs[0].decode().splitlines():
        if line.startswith("SERVICE_METRICS "):
            got = json.loads(line[len("SERVICE_METRICS "):])
    assert got is not None, outputs[0].decode()

    # every run leased the FULL global mesh (the sharded feed's shape)
    assert len(got["placements"]) == N_SUITES, got["placements"]
    for placement in got["placements"]:
        assert placement.get("ndev") == 8, placement

    # backend gate: on a CPU backend the cross-process collective scan
    # cannot execute — the resilience layer quarantines every batch
    # UNIFORMLY on both hosts (no one-sided hang; the placement, run
    # queue and sharded feed all worked) and each run degrades to an
    # empty-state ERROR. Raise the real reason so the test's
    # backend-keyed xfail reads it; runs for real on a multi-host TPU
    # slice (ROADMAP item 5).
    backend_wall = [
        run
        for run in got["runs"]
        if "Multiprocess computations aren't implemented"
        in run.get("degradation", "")
    ]
    if backend_wall:
        raise RuntimeError(
            "cross-process collective scan unavailable on this backend "
            "(CPU has no multi-process computations); fleet placement/"
            "queue/sharded-feed all executed and quarantined uniformly "
            f"— degradation: {backend_wall[0]['degradation']}"
        )

    # whole-table single-process reference: same suites, same data
    from deequ_tpu import Dataset
    from deequ_tpu.verification import VerificationSuite

    exec(SUITE_SRC, globals())
    whole = Dataset.from_arrow(table)
    for i, run in enumerate(got["runs"]):
        solo = VerificationSuite.do_verification_run(
            whole, make_suite(i)  # noqa: F821 — bound by exec above
        )
        def _metric_value(m):
            try:
                return m.value.get()
            except Exception:  # noqa: BLE001 — failed metric -> text
                return str(getattr(m, "value", m))

        want = {
            str(a): _metric_value(m)
            for a, m in dict(solo.metrics).items()
        }
        assert set(run["metrics"]) == set(want), (
            set(run["metrics"]) ^ set(want)
        )
        for name, have in run["metrics"].items():
            w = want[name]
            try:
                have_f, want_f = float(have), float(w)
            except (TypeError, ValueError):
                assert str(have) == str(w), (name, have, w)
                continue
            assert abs(have_f - want_f) <= 1e-9 * max(
                1.0, abs(want_f)
            ), (name, have_f, want_f)
        print(f"suite {i}: fleet metrics == whole-table ({run['status']})")
    print(
        "distributed service (2 processes, loopback, sharded feed): "
        "fleet metrics == whole-table"
    )


if __name__ == "__main__":
    main()
