"""End-to-end Check DSL + VerificationSuite tests (reference shape:
``checks/CheckTest.scala`` + ``VerificationSuiteTest.scala``)."""

import pytest

from deequ_tpu import Check, CheckLevel, CheckStatus, VerificationSuite
from deequ_tpu.checks import ConstrainableDataTypes
from deequ_tpu.constraints import ConstraintStatus
from fixtures import df_full, df_missing, df_numeric, df_strings, df_unique


def run(data, *checks):
    builder = VerificationSuite().on_data(data)
    for check in checks:
        builder = builder.add_check(check)
    return builder.run()


class TestBasicChecks:
    def test_success(self):
        check = (
            Check(CheckLevel.ERROR, "basic")
            .has_size(lambda s: s == 4)
            .is_complete("att1")
            .has_completeness("att1", lambda c: c == 1.0)
        )
        result = run(df_full(), check)
        assert result.status == CheckStatus.SUCCESS

    def test_failure(self):
        check = Check(CheckLevel.ERROR, "basic").is_complete("att2")
        result = run(df_missing(), check)
        assert result.status == CheckStatus.ERROR

    def test_warning_level(self):
        check = Check(CheckLevel.WARNING, "warn").is_complete("att2")
        result = run(df_missing(), check)
        assert result.status == CheckStatus.WARNING

    def test_mixed_status_takes_worst(self):
        ok = Check(CheckLevel.ERROR, "ok").has_size(lambda s: s == 12)
        warn = Check(CheckLevel.WARNING, "warn").is_complete("att2")
        result = run(df_missing(), ok, warn)
        assert result.status == CheckStatus.WARNING

    def test_constraint_messages(self):
        check = Check(CheckLevel.ERROR, "sized").has_size(lambda s: s > 100)
        result = run(df_full(), check)
        (check_result,) = result.check_results.values()
        (constraint_result,) = check_result.constraint_results
        assert constraint_result.status == ConstraintStatus.FAILURE
        assert "4.0" in constraint_result.message


class TestNumericChecks:
    def test_stats(self):
        check = (
            Check(CheckLevel.ERROR, "numbers")
            .has_min("att1", lambda v: v == 1.0)
            .has_max("att1", lambda v: v == 6.0)
            .has_mean("att1", lambda v: v == 3.5)
            .has_sum("att1", lambda v: v == 21.0)
            .has_standard_deviation("att1", lambda v: abs(v - 1.707825) < 1e-5)
        )
        assert run(df_numeric(), check).status == CheckStatus.SUCCESS

    def test_is_non_negative_and_positive(self):
        check = (
            Check(CheckLevel.ERROR, "sign")
            .is_non_negative("att2")
            .is_positive("att1")
        )
        assert run(df_numeric(), check).status == CheckStatus.SUCCESS

    def test_column_comparisons(self):
        check = Check(CheckLevel.ERROR, "cmp").is_less_than_or_equal_to(
            "att2", "att1", lambda v: v >= 0.5
        )
        assert run(df_numeric(), check).status == CheckStatus.SUCCESS

    def test_correlation(self):
        check = Check(CheckLevel.ERROR, "corr").has_correlation(
            "att1", "att2", lambda v: v > 0.9
        )
        assert run(df_numeric(), check).status == CheckStatus.SUCCESS


class TestUniquenessChecks:
    def test_is_unique(self):
        check = Check(CheckLevel.ERROR, "uni").is_unique("unique")
        assert run(df_unique(), check).status == CheckStatus.SUCCESS

    def test_has_uniqueness_multi(self):
        check = Check(CheckLevel.ERROR, "uni").has_uniqueness(
            ("att1", "att2"), lambda v: v == 0.5
        )
        assert run(df_full(), check).status == CheckStatus.SUCCESS

    def test_distinctness(self):
        check = Check(CheckLevel.ERROR, "d").has_distinctness(
            "non_unique", lambda v: v == 0.6
        )
        assert run(df_unique(), check).status == CheckStatus.SUCCESS

    def test_number_of_distinct_values(self):
        check = Check(CheckLevel.ERROR, "n").has_number_of_distinct_values(
            "half", lambda v: v == 4
        )
        assert run(df_unique(), check).status == CheckStatus.SUCCESS


class TestPredicatesAndPatterns:
    def test_satisfies(self):
        # att2 - att1 > 0 holds for rows 4..6 only
        check = Check(CheckLevel.ERROR, "sat").satisfies(
            "att2 - att1 > 0", "att2 exceeds att1", lambda v: v == 0.5
        )
        assert run(df_numeric(), check).status == CheckStatus.SUCCESS

    def test_is_contained_in(self):
        check = Check(CheckLevel.ERROR, "in").is_contained_in(
            "att1", ["a", "b"]
        )
        assert run(df_full(), check).status == CheckStatus.SUCCESS

    def test_is_in_range(self):
        check = Check(CheckLevel.ERROR, "range").is_in_range("att1", 1, 6)
        assert run(df_numeric(), check).status == CheckStatus.SUCCESS

    def test_contains_email(self):
        check = Check(CheckLevel.ERROR, "email").contains_email(
            "email", lambda v: v == 0.75
        )
        assert run(df_strings(), check).status == CheckStatus.SUCCESS

    def test_has_pattern_with_where(self):
        check = (
            Check(CheckLevel.ERROR, "f")
            .has_completeness("att2", lambda c: c == 1.0)
            .where("att1 = 'b'")
        )
        assert run(df_missing(), check).status == CheckStatus.ERROR

    def test_where_filter_success(self):
        # rows with att2 = 0 have att1 in 1..3
        check = (
            Check(CheckLevel.ERROR, "f")
            .has_max("att1", lambda v: v == 3.0)
            .where("att2 = 0")
        )
        assert run(df_numeric(), check).status == CheckStatus.SUCCESS


class TestDataTypeChecks:
    def test_has_data_type(self):
        check = Check(CheckLevel.ERROR, "dt").has_data_type(
            "typed", ConstrainableDataTypes.NUMERIC, lambda v: v == 0.5
        )
        assert run(df_strings(), check).status == CheckStatus.SUCCESS


class TestMetricsExport:
    def test_success_metrics_records(self):
        check = (
            Check(CheckLevel.ERROR, "m")
            .has_size(lambda s: s == 4)
            .is_complete("att1")
        )
        result = run(df_full(), check)
        records = result.success_metrics_as_records()
        by_name = {(r["name"], r["instance"]): r["value"] for r in records}
        assert by_name[("Size", "*")] == 4.0
        assert by_name[("Completeness", "att1")] == 1.0

    def test_missing_analysis(self):
        from deequ_tpu.analyzers.runner import AnalyzerContext

        check = Check(CheckLevel.ERROR, "m").has_size(lambda s: True)
        result = VerificationSuite.evaluate([check], AnalyzerContext.empty())
        assert result.status == CheckStatus.ERROR
