"""Histogram metric: value -> (absolute count, ratio) distribution.

Reference: ``src/main/scala/com/amazon/deequ/metrics/Distribution.scala``
(SURVEY.md §2.1).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Sequence

from deequ_tpu.metrics.metric import DoubleMetric, Entity, Metric
from deequ_tpu.utils.trylike import Success


@dataclass(frozen=True)
class DistributionValue:
    absolute: int
    ratio: float


@dataclass(frozen=True)
class Distribution:
    """Value distribution over (up to ``max_detail_bins``) observed values."""

    values: Dict[str, DistributionValue]
    number_of_bins: int

    def __getitem__(self, key: str) -> DistributionValue:
        return self.values[key]

    def argmax(self) -> str:
        return max(self.values.items(), key=lambda kv: kv[1].absolute)[0]


@dataclass(frozen=True)
class HistogramMetric(Metric[Distribution]):
    """Full value distribution of a column (reference: HistogramMetric)."""

    def flatten(self) -> Sequence[DoubleMetric]:
        if self.value.is_failure:
            return (
                DoubleMetric(
                    self.entity, f"{self.name}.bins", self.instance, self.value
                ),
            )
        dist = self.value.get()
        out = [
            DoubleMetric(
                self.entity,
                f"{self.name}.bins",
                self.instance,
                Success(float(dist.number_of_bins)),
            )
        ]
        for key, dv in dist.values.items():
            out.append(
                DoubleMetric(
                    self.entity,
                    f"{self.name}.abs.{key}",
                    self.instance,
                    Success(float(dv.absolute)),
                )
            )
            out.append(
                DoubleMetric(
                    self.entity,
                    f"{self.name}.ratio.{key}",
                    self.instance,
                    Success(dv.ratio),
                )
            )
        return tuple(out)

    @staticmethod
    def from_counts(
        name: str, instance: str, counts: Dict[str, int], total: int
    ) -> "HistogramMetric":
        dist = Distribution(
            {
                k: DistributionValue(int(c), (c / total) if total else 0.0)
                for k, c in counts.items()
            },
            number_of_bins=len(counts),
        )
        return HistogramMetric(Entity.COLUMN, name, instance, Success(dist))
