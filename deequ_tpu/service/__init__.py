"""Multi-tenant verification service (docs/SERVICE.md).

PRs 3-6 built every primitive a long-lived verification server needs —
FIFO admission with a bytes watermark, deadlines, cooperative cancel,
SIGTERM draining, checkpoint/resume, quarantine degradation, warm plan
precompilation — but only reachable one ``run()`` at a time from one
caller. This package composes them into the always-on daemon the paper
pitches (Schelter et al., PVLDB 11(12): a SHARED platform many teams
submit suites to):

- ``RunQueue`` + ``Scheduler``: thread-safe submissions from many
  concurrent clients, priority classes with an anti-starvation
  interactive reserve, per-tenant quotas, deadline-aware dequeue;
- ``DatasetCache``: one device placement per shared table, however
  many tenants verify it, with bytes-watermark LRU eviction;
- ``PlanCache``: the service-level view over the engine's cross-run
  jitted plan cache — warmed at startup via ``tools/warmup.py``, so
  steady state recompiles nothing;
- ``VerificationService``: the facade — ``submit()`` returns a
  ``RunHandle`` (poll/wait/cancel; results carry degradation and
  interruption provenance exactly like a direct run).

Clock discipline: NO module here may call ``time.time``/``time.sleep``
directly (enforced by tools/telemetry_lint.py) — all timing rides the
injectable clocks from ``engine/deadline.py`` so every scheduling
behavior is testable on fake time. Execution always goes through the
runner's admission layer, never ``engine.run_scan`` directly (also
lint-enforced).
"""

from deequ_tpu.service.autoscale import AutoscaleController
from deequ_tpu.service.caches import DatasetCache, PlanCache
from deequ_tpu.service.journal import RunJournal
from deequ_tpu.service.preempt import (
    PreemptionController,
    preempt_checkpoint_evidence,
    run_cancel_token,
)
from deequ_tpu.service.placement import (
    DevicePool,
    ElasticPlacer,
    MeshCache,
    PlacementLease,
    PlacementPolicy,
)
from deequ_tpu.service.queue import (
    Priority,
    QuotaExceeded,
    RunHandle,
    RunQueue,
    RunState,
    RunTicket,
)
from deequ_tpu.service.scheduler import Scheduler
from deequ_tpu.service.service import (
    RunRequest,
    ServiceOverloaded,
    VerificationService,
)

__all__ = [
    "AutoscaleController",
    "DatasetCache",
    "DevicePool",
    "ElasticPlacer",
    "MeshCache",
    "PlacementLease",
    "PlacementPolicy",
    "PlanCache",
    "PreemptionController",
    "Priority",
    "QuotaExceeded",
    "RunHandle",
    "RunJournal",
    "RunQueue",
    "RunState",
    "RunTicket",
    "RunRequest",
    "Scheduler",
    "ServiceOverloaded",
    "VerificationService",
    "preempt_checkpoint_evidence",
    "run_cancel_token",
]
