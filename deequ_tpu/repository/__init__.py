from deequ_tpu.repository.base import (
    AnalysisResult,
    InMemoryMetricsRepository,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)

__all__ = [
    "AnalysisResult",
    "InMemoryMetricsRepository",
    "MetricsRepository",
    "MetricsRepositoryMultipleResultsLoader",
    "ResultKey",
]
