"""Anomaly-check wiring into VerificationSuite.

Reference: ``VerificationRunBuilder.addAnomalyCheck`` (SURVEY.md §3.5):
synthesize a Check whose constraint assertion loads the metric history
from the repository and asks the strategy whether the new point is
anomalous. Driver-only; no data access beyond the metric itself.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.anomalydetection.base import (
    AnomalyDetectionStrategy,
    AnomalyDetector,
    DataPoint,
)
from deequ_tpu.checks.check import Check, CheckLevel
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    NamedConstraint,
)


@dataclass
class AnomalyCheckConfig:
    level: CheckLevel = CheckLevel.WARNING
    description: str = "Anomaly check"
    with_tag_values: Dict[str, str] = field(default_factory=dict)
    after_date: Optional[int] = None
    before_date: Optional[int] = None


def build_anomaly_check(
    repository,
    strategy: AnomalyDetectionStrategy,
    analyzer: Analyzer,
    config: AnomalyCheckConfig,
    current_key=None,
) -> Check:
    def assertion(metric_value: float) -> bool:
        loader = repository.load().for_analyzers([analyzer])
        if config.with_tag_values:
            loader = loader.with_tag_values(config.with_tag_values)
        if config.after_date is not None:
            loader = loader.after(config.after_date)
        if config.before_date is not None:
            loader = loader.before(config.before_date)
        now = (
            current_key.dataset_date
            if current_key is not None
            else _max_time(loader) + 1
        )
        history = []
        for result in loader.get():
            if (
                current_key is not None
                and result.result_key.dataset_date >= now
            ):
                continue  # the in-flight run's own (or newer) points
            metric = result.analyzer_context.metric(analyzer)
            if metric is not None and metric.value.is_success:
                history.append(
                    DataPoint(
                        result.result_key.dataset_date,
                        float(metric.value.get()),
                    )
                )
        detection = AnomalyDetector(strategy).is_new_point_anomalous(
            history, DataPoint(now, float(metric_value))
        )
        return not detection.is_anomalous

    constraint = NamedConstraint(
        AnalysisBasedConstraint(analyzer, assertion),
        f"AnomalyConstraint({analyzer.name}({analyzer.instance}))",
    )
    return Check(config.level, config.description).add_constraint(constraint)


def _max_time(loader) -> int:
    results = loader.get()
    return max(
        (r.result_key.dataset_date for r in results), default=0
    )
