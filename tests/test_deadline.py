"""Deadlines, cooperative cancellation, and watchdog supervision
(docs/RESILIENCE.md, "Deadlines & cancellation").

Every timing-sensitive test runs on an injected ManualClock: fake time
is advanced only by the fault that is actually hanging (hang ticks, a
slow batch's one-shot delay), never by a free-running timer — so no
test here sleeps wall-clock time, and an autouse guard fails any test
that tries. The load-bearing differentials: a stalled batch flows
through PR 3's retry -> quarantine path and the run COMPLETES degraded;
a cancelled run checkpoints its final cursor and the resumed run is
bit-identical to an uninterrupted one, on resident, streaming and mesh
paths alike.
"""

import threading
import time

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxQuantile,
    Completeness,
    Mean,
    Size,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.engine.deadline import (
    AdmissionController,
    CancelToken,
    DeadlineExceeded,
    ManualClock,
    RunBudget,
    RunCancelled,
    ScanSupervisor,
    install_graceful_shutdown,
    reset_shutdown_token,
    shutdown_installed,
    shutdown_token,
)
from deequ_tpu.engine.resilience import RetryPolicy, ScanStalled
from deequ_tpu.engine.scan import AnalysisEngine, active_prefetch_workers
from deequ_tpu.io.state_provider import ScanCheckpointer
from deequ_tpu.io.storage import LocalStorage, interprocess_lock
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.testing.faults import FaultInjectingDataset
from deequ_tpu.verification.suite import VerificationSuite


@pytest.fixture(autouse=True)
def _no_wall_sleeps(monkeypatch):
    """The module contract: supervision tests never wall-sleep. Any
    sleep over a second means a fake-clock path regressed into real
    waiting — fail the test rather than hang CI."""
    real_sleep = time.sleep

    def guarded(seconds):
        assert seconds <= 1.0, (
            f"test slept {seconds}s of real time — deadline tests must "
            "run on the injected ManualClock"
        )
        real_sleep(seconds)

    monkeypatch.setattr(time, "sleep", guarded)


def _no_sleep(_s: float) -> None:
    pass


FAST_RETRY = RetryPolicy(max_attempts=3, sleep=_no_sleep)


def _table_data(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.normal(size=n).tolist(),
        "g": (np.arange(n) % 7).tolist(),
    }


ANALYZERS = [
    Size(),
    Completeness("a"),
    Mean("a"),
    ApproxQuantile("a", 0.5),
    Uniqueness(["g"]),
]


def _metric_values(ctx, analyzers=ANALYZERS):
    out = []
    for a in analyzers:
        value = ctx.metric(a).value
        assert value.is_success, (a, value)
        out.append((str(a), value.get()))
    return out


def _mode_setup(mode, cpu_mesh):
    if mode == "resident":
        return (lambda **kw: AnalysisEngine(**kw)), dict(
            device_cache_bytes=1 << 30, batch_size=104
        )
    if mode == "streaming":
        return (lambda **kw: AnalysisEngine(**kw)), dict(
            device_cache_bytes=0, batch_size=104
        )
    assert mode == "mesh"
    return (lambda **kw: AnalysisEngine(mesh=cpu_mesh, **kw)), dict(
        device_cache_bytes=0, batch_size=104
    )


MODES = ["resident", "streaming", "mesh"]


def _stall_budget(stall_s=1.0, deadline_s=10_000.0):
    """A generous fake-clock envelope: only injected faults advance the
    clock, so the deadline never fires unless a test advances past it."""
    return RunBudget(
        deadline_s=deadline_s, stall_s=stall_s, clock=ManualClock()
    )


# --------------------------------------------------------------------------
# CancelToken
# --------------------------------------------------------------------------


class TestCancelToken:
    def test_cancel_sets_reason_and_raises(self):
        token = CancelToken()
        assert not token.cancelled and token.reason is None
        token.raise_if_cancelled()  # no-op while active
        token.cancel("operator said stop")
        assert token.cancelled
        assert token.reason == "operator said stop"
        with pytest.raises(RunCancelled, match="operator said stop"):
            token.raise_if_cancelled()
        # idempotent: the first reason wins
        token.cancel("second")
        assert token.reason == "operator said stop"

    def test_parent_cancels_children_transitively(self):
        parent = CancelToken()
        child = parent.child()
        grandchild = child.child()
        parent.cancel("drain")
        assert child.cancelled and grandchild.cancelled
        assert grandchild.reason == "drain"

    def test_child_cancel_leaves_parent_active(self):
        parent = CancelToken()
        child = parent.child()
        child.cancel("just me")
        assert child.cancelled
        assert not parent.cancelled

    def test_linking_to_cancelled_parent_cancels_immediately(self):
        parent = CancelToken()
        parent.cancel("already gone")
        child = parent.child()
        assert child.cancelled and child.reason == "already gone"

    def test_wait(self):
        token = CancelToken()
        assert token.wait(timeout=0) is False
        token.cancel()
        assert token.wait(timeout=0) is True


# --------------------------------------------------------------------------
# RunBudget on a ManualClock
# --------------------------------------------------------------------------


class TestRunBudget:
    def test_deadline_on_manual_clock(self):
        clock = ManualClock()
        budget = RunBudget(deadline_s=10.0, clock=clock)
        budget.start()
        assert budget.remaining() == 10.0
        clock.advance(4.0)
        assert budget.elapsed() == 4.0
        assert budget.remaining() == 6.0
        assert not budget.expired()
        budget.check()
        clock.advance(7.0)
        assert budget.expired()
        with pytest.raises(DeadlineExceeded, match="10.0s"):
            budget.check()

    def test_start_is_idempotent(self):
        clock = ManualClock()
        budget = RunBudget(deadline_s=10.0, clock=clock)
        budget.start()
        clock.advance(5.0)
        budget.start()  # the profiler's later passes must NOT reset it
        assert budget.elapsed() == 5.0

    def test_no_deadline_never_expires(self):
        budget = RunBudget(stall_s=1.0, clock=ManualClock())
        budget.start()
        budget.clock.advance(1e9)
        assert budget.remaining() is None
        assert not budget.expired()
        budget.check()

    def test_unstarted_budget_has_zero_elapsed(self):
        assert RunBudget(deadline_s=1.0, clock=ManualClock()).elapsed() == 0.0


# --------------------------------------------------------------------------
# ScanSupervisor: one stall rule, three observation points
# --------------------------------------------------------------------------


class TestScanSupervisor:
    def test_on_wait_raises_after_stall_window(self):
        sup = ScanSupervisor(_stall_budget(stall_s=2.0))
        tm = get_telemetry()
        before = tm.counter("engine.stalls_detected").value
        sup.clock.advance(1.0)
        sup.on_wait()  # within the window: nothing
        sup.clock.advance(1.5)
        with pytest.raises(ScanStalled, match="stalled source"):
            sup.on_wait()
        assert tm.counter("engine.stalls_detected").value == before + 1
        # the raise re-armed the window — the retry must get fresh time
        sup.on_wait()

    def test_note_arrival_catches_slow_batch(self):
        sup = ScanSupervisor(_stall_budget(stall_s=2.0))
        sup.clock.advance(1.0)
        sup.note_arrival()  # timely: re-arms
        sup.clock.advance(3.0)
        with pytest.raises(ScanStalled, match="stall limit"):
            sup.note_arrival()

    def test_watchdog_check_releases_armed_source(self):
        sup = ScanSupervisor(_stall_budget(stall_s=2.0))
        event = sup.arm_source()
        sup.watchdog_check()
        assert not event.is_set()
        sup.clock.advance(3.0)
        sup.watchdog_check()
        assert event.is_set()
        assert sup.stalls == 1
        # a fresh arm (iterator restart) is a fresh, un-set event
        assert not sup.arm_source().is_set()

    def test_cancel_reported_before_deadline(self):
        token = CancelToken()
        budget = RunBudget(deadline_s=1.0, clock=ManualClock())
        sup = ScanSupervisor(budget, [token])
        sup.clock.advance(5.0)
        token.cancel("explicit")
        # both fired; the explicit cancel is the more specific reason
        with pytest.raises(RunCancelled, match="explicit"):
            sup.check()

    def test_watchdog_releases_source_on_cancel(self):
        token = CancelToken()
        sup = ScanSupervisor(None, [token])
        event = sup.arm_source()
        token.cancel()
        sup.watchdog_check()
        assert event.is_set()


# --------------------------------------------------------------------------
# Admission control
# --------------------------------------------------------------------------


def _spin_until(predicate, what, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while not predicate():
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        time.sleep(0.001)


class TestAdmissionController:
    def test_fifo_ordering(self):
        ctl = AdmissionController()
        ctl.acquire(1)  # occupy the only slot
        order = []

        def worker(n):
            ctl.acquire(1)
            order.append(n)
            ctl.release()

        t1 = threading.Thread(target=worker, args=(1,))
        t1.start()
        _spin_until(lambda: ctl.snapshot()["queued"] == 1, "t1 queued")
        t2 = threading.Thread(target=worker, args=(2,))
        t2.start()
        _spin_until(lambda: ctl.snapshot()["queued"] == 2, "t2 queued")
        ctl.release()
        t1.join(timeout=5)
        t2.join(timeout=5)
        assert order == [1, 2]
        assert ctl.snapshot() == {
            "active": 0, "queued": 0, "active_bytes": 0,
        }

    def test_queued_run_expires_under_its_deadline(self):
        ctl = AdmissionController()
        ctl.acquire(1)
        budget = RunBudget(deadline_s=5.0, clock=ManualClock())
        budget.start()
        budget.clock.advance(10.0)
        with pytest.raises(DeadlineExceeded, match="queued for admission"):
            ctl.acquire(1, budget=budget)
        # the dead ticket was removed — the queue is clean
        assert ctl.snapshot()["queued"] == 0
        ctl.release()

    def test_queued_run_cancellable(self):
        ctl = AdmissionController()
        ctl.acquire(1)
        token = CancelToken()
        token.cancel("gave up waiting")
        with pytest.raises(RunCancelled, match="gave up waiting"):
            ctl.acquire(1, tokens=[token])
        assert ctl.snapshot()["queued"] == 0
        ctl.release()

    def test_acquire_starts_budget_epoch(self):
        ctl = AdmissionController()
        budget = RunBudget(deadline_s=5.0, clock=ManualClock())
        ctl.acquire(4, budget=budget)  # free slot: admitted immediately
        assert budget._started_at is not None
        ctl.release()

    def test_config_knob_end_to_end(self):
        from deequ_tpu.engine.deadline import admission_controller

        tm = get_telemetry()
        queued_before = tm.counter("engine.runs_queued").value
        with config.configure(max_concurrent_runs=1):
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_pydict({"x": [1.0, 2.0, 3.0]}), [Size()]
            )
        assert ctx.metric(Size()).value.get() == 3
        # uncontended: admitted without queueing, slot released after
        assert tm.counter("engine.runs_queued").value == queued_before
        assert admission_controller().snapshot()["active"] == 0


# --------------------------------------------------------------------------
# Cross-process repository lock + durable writes (io satellites)
# --------------------------------------------------------------------------


class TestInterprocessLock:
    def test_serializes_across_file_descriptors(self, tmp_path):
        """flock conflicts between separate opens of the lock file even
        in one process — exactly how two worker PROCESSES would contend."""
        lock_path = str(tmp_path / "repo.lock")
        entered = threading.Event()
        released = threading.Event()

        def contender():
            with interprocess_lock(lock_path):
                entered.set()

        with interprocess_lock(lock_path):
            t = threading.Thread(target=contender)
            t.start()
            # the second acquire must block while we hold the lock
            assert not entered.wait(timeout=0.1)
            released.set()
        t.join(timeout=5)
        assert entered.is_set()

    def test_repository_save_is_lost_update_free(self, tmp_path):
        """Two repository INSTANCES on one file (distinct in-process
        locks, like two workers) appending concurrently: every save must
        survive the read-modify-write."""
        from deequ_tpu.repository.base import AnalysisResult, ResultKey
        from deequ_tpu.repository.fs import FileSystemMetricsRepository

        path = str(tmp_path / "metrics.json")
        ctx = AnalysisRunner.do_analysis_run(
            Dataset.from_pydict({"x": [1.0, 2.0]}), [Size()]
        )
        repos = [
            FileSystemMetricsRepository(path),
            FileSystemMetricsRepository(path),
        ]

        def writer(repo, worker):
            for i in range(10):
                key = ResultKey.of(
                    1000 + i, {"worker": str(worker), "i": str(i)}
                )
                repo.save(AnalysisResult(key, ctx))

        threads = [
            threading.Thread(target=writer, args=(repo, w))
            for w, repo in enumerate(repos)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(repos[0].load().get()) == 20


class TestDurableWrites:
    def test_durable_local_write_round_trips(self, tmp_path):
        storage = LocalStorage(str(tmp_path))
        storage.write_bytes("ckpt.bin", b"payload", durable=True)
        assert storage.read_bytes("ckpt.bin") == b"payload"
        # no temp-file orphans after the fsync + replace
        assert storage.list_keys() == ["ckpt.bin"]

    def test_checkpointer_falls_back_on_legacy_storage(self, tmp_path):
        """A Storage subclass predating ``durable=`` still checkpoints."""
        from deequ_tpu.io.state_provider import ScanCursor

        class LegacyStorage:
            def __init__(self):
                self.blobs = {}

            def read_bytes(self, key):
                return self.blobs.get(key)

            def write_bytes(self, key, data):  # no durable kwarg
                self.blobs[key] = bytes(data)

        ckpt = ScanCheckpointer(str(tmp_path))
        ckpt._storage = LegacyStorage()
        cursor = ScanCursor(
            batch_index=3, row_offset=312,
            source_fingerprint="fp", batch_size=104,
        )
        ckpt.save(cursor, "tok", (), {}, None)
        assert ckpt.load("fp", "tok")["cursor"].batch_index == 3


# --------------------------------------------------------------------------
# Engine-level: stall -> retry -> quarantine, cancel -> checkpoint ->
# resume, deadline -> partial metrics — all modes, all fake-clock
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", MODES)
class TestEngineSupervision:
    def test_stall_retried_then_bit_identical(self, mode, cpu_mesh):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        data = _table_data()
        with config.configure(scan_retry=FAST_RETRY, **opts):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS,
                    engine=make_engine(),
                )
            )
            budget = _stall_budget(stall_s=1.0)
            ds = FaultInjectingDataset(
                Dataset.from_pydict(data),
                hang_at_batch={3: 1},
                clock=budget.clock,
            )
            tm = get_telemetry()
            stalls_before = tm.counter("engine.stalls_detected").value
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine(budget=budget)
            )
        assert _metric_values(ctx) == ref
        assert ("hang", 3) in ds.faults_fired
        assert tm.counter("engine.stalls_detected").value > stalls_before
        degr = ctx.degradation
        assert degr is not None and degr.retries >= 1
        assert not degr.is_degraded
        assert ctx.interruption is None  # stalls degrade, never interrupt

    def test_persistent_stall_quarantined_and_run_completes(
        self, mode, cpu_mesh
    ):
        """THE acceptance path: a batch that hangs every attempt is
        detected by the watchdog, retried, quarantined — and the run
        COMPLETES degraded, entirely on the fake clock."""
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        budget = _stall_budget(stall_s=1.0)
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()),
            hang_at_batch={3: 99},  # re-hangs on every retry
            clock=budget.clock,
        )
        with config.configure(scan_retry=FAST_RETRY, **opts):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine(budget=budget)
            )
        degr = ctx.degradation
        assert degr is not None and degr.is_degraded
        assert degr.batches_quarantined == 1
        assert degr.rows_skipped == 104
        assert degr.failures[0].error_class == "ScanStalled"
        # the run finished: every metric computed over the partial data
        assert ctx.metric(Size()).value.get() == 1000 - 104
        # well inside the (fake) deadline, and no interrupt was recorded
        assert not budget.expired()
        assert ctx.interruption is None
        # teardown joined every prefetch worker — no thread leak
        assert active_prefetch_workers() == []

    def test_cancel_mid_scan_checkpoints_then_resume_bit_identical(
        self, mode, cpu_mesh, tmp_path
    ):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        data = _table_data()
        tm = get_telemetry()
        with config.configure(
            scan_retry=FAST_RETRY, checkpoint_every_batches=100, **opts
        ):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS,
                    engine=make_engine(),
                )
            )
            token = CancelToken()
            ds = FaultInjectingDataset(
                Dataset.from_pydict(data),
                on_batch={5: lambda: token.cancel("user clicked stop")},
            )
            ckpt = ScanCheckpointer(str(tmp_path))
            cancelled_before = tm.counter("engine.runs_cancelled").value
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS,
                engine=make_engine(checkpointer=ckpt), cancel=token,
            )
            # the interrupted run RETURNED (never raised), with
            # provenance and a persisted resume cursor
            interruption = ctx.interruption
            assert interruption is not None
            assert interruption.kind == "cancelled"
            assert "user clicked stop" in interruption.reason
            assert interruption.checkpointed
            assert 0 < interruption.batch_index < 10
            assert tm.counter("engine.runs_cancelled").value > cancelled_before
            assert ckpt._storage.list_keys("scan-ckpt-")
            # partial metrics cover exactly the checkpointed batches
            size = ctx.metric(Size()).value.get()
            assert size == interruption.batch_index * 104

            resumes_before = tm.counter("engine.resumes").value
            ctx2 = AnalysisRunner.do_analysis_run(
                Dataset.from_pydict(data), ANALYZERS,
                engine=make_engine(checkpointer=ckpt),
            )
            assert tm.counter("engine.resumes").value - resumes_before == 1
        assert _metric_values(ctx2) == ref
        assert ctx2.interruption is None
        # completion cleared the cursor
        assert ckpt._storage.list_keys("scan-ckpt-") == []
        assert active_prefetch_workers() == []

    def test_pre_cancelled_run_returns_cleanly(self, mode, cpu_mesh):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        token = CancelToken()
        token.cancel("cancelled before start")
        with config.configure(**opts):
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_pydict(_table_data()), ANALYZERS,
                engine=make_engine(), cancel=token,
            )
        assert ctx.interruption is not None
        assert ctx.interruption.batch_index == 0
        assert not ctx.interruption.checkpointed
        # pristine init states: zero rows scanned
        assert ctx.metric(Size()).value.get() == 0


class TestDeadlineMidScan:
    def test_slow_batch_burns_deadline_partial_metrics(self):
        # resident mode: the source is consumed on the scan thread, so
        # the fake-clock advance lands between two exact batches (the
        # streaming prefetch thread would race ahead of the consumer)
        budget = RunBudget(deadline_s=10.0, clock=ManualClock())
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()),
            slow_batch={2: 50.0},  # one batch eats 5x the deadline
            clock=budget.clock,
        )
        tm = get_telemetry()
        before = tm.counter("engine.deadline_exceeded").value
        with config.configure(device_cache_bytes=1 << 30, batch_size=104):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=AnalysisEngine(budget=budget)
            )
        interruption = ctx.interruption
        assert interruption is not None and interruption.kind == "deadline"
        assert tm.counter("engine.deadline_exceeded").value == before + 1
        # exactly batches 0 and 1 finished before the slow batch burned
        # the envelope; metrics are partial but correct over them
        assert interruption.batch_index == 2
        assert ctx.metric(Size()).value.get() == 2 * 104

    def test_config_deadline_knob(self):
        # a sub-nanosecond budget from config: the run exits through
        # the interruption path without any explicit RunBudget
        with config.configure(
            run_deadline_seconds=1e-9, device_cache_bytes=0, batch_size=104
        ):
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_pydict(_table_data()), ANALYZERS
            )
        assert ctx.interruption is not None
        assert ctx.interruption.kind == "deadline"


# --------------------------------------------------------------------------
# Verification flooring + builder surface
# --------------------------------------------------------------------------


class TestInterruptionFloorsVerification:
    def _interrupted_result(self, policy):
        token = CancelToken()
        # checks that PASS on the partial data — status movement below
        # comes from the interruption floor alone
        check = Check(CheckLevel.ERROR, "robust").has_size(lambda s: s > 0)
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()),
            on_batch={5: lambda: token.cancel("drain")},
        )
        with config.configure(
            device_cache_bytes=0, batch_size=104,
            degradation_policy=policy,
        ):
            return VerificationSuite.do_verification_run(
                ds, [check], cancel=token
            )

    def test_fail_policy_floors_to_error(self):
        result = self._interrupted_result("fail")
        assert result.status == CheckStatus.ERROR
        assert result.interruption is not None
        assert result.interruption.kind == "cancelled"

    def test_warn_policy_floors_to_warning(self):
        result = self._interrupted_result("warn")
        assert result.status == CheckStatus.WARNING

    def test_tolerate_policy_keeps_check_status(self):
        result = self._interrupted_result("tolerate")
        assert result.status == CheckStatus.SUCCESS
        # the provenance still rides the result for consumers
        assert result.interruption is not None

    def test_builder_deadline_and_cancel_wire_through(self):
        token = CancelToken()
        token.cancel("pre-cancelled")
        result = (
            VerificationSuite()
            .on_data(Dataset.from_pydict(_table_data()))
            .add_check(
                Check(CheckLevel.ERROR, "x").has_size(lambda s: s == 1000)
            )
            .with_cancel(token)
            .run()
        )
        assert result.interruption is not None


# --------------------------------------------------------------------------
# Graceful shutdown (SIGTERM)
# --------------------------------------------------------------------------


class TestGracefulShutdown:
    def test_sigterm_maps_to_shutdown_token(self):
        import signal

        uninstall = install_graceful_shutdown()
        try:
            assert shutdown_installed()
            assert not shutdown_token().cancelled
            signal.raise_signal(signal.SIGTERM)
            assert shutdown_token().cancelled
            assert "SIGTERM" in shutdown_token().reason
        finally:
            uninstall()
            reset_shutdown_token()
        assert not shutdown_installed()
        assert not shutdown_token().cancelled

    def test_sigterm_mid_scan_exits_with_provenance(self):
        import signal

        uninstall = install_graceful_shutdown()
        try:
            # resident mode: the hook runs on the main thread, so the
            # Python-level handler fires at the next bytecode boundary
            ds = FaultInjectingDataset(
                Dataset.from_pydict(_table_data()),
                on_batch={3: lambda: signal.raise_signal(signal.SIGTERM)},
            )
            with config.configure(
                device_cache_bytes=1 << 30, batch_size=104
            ):
                ctx = AnalysisRunner.do_analysis_run(ds, ANALYZERS)
            assert ctx.interruption is not None
            assert ctx.interruption.kind == "cancelled"
            assert "SIGTERM" in ctx.interruption.reason
        finally:
            uninstall()
            reset_shutdown_token()


# --------------------------------------------------------------------------
# Profiler: one envelope across passes
# --------------------------------------------------------------------------


class TestProfilerEnvelope:
    def test_interrupted_pass_skips_the_rest(self):
        from deequ_tpu.profiles.profiler import ColumnProfiler

        data = Dataset.from_pydict(_table_data())
        tm = get_telemetry()
        runs_before = tm.counter("runner.runs").value
        token = CancelToken()
        token.cancel("budget spent elsewhere")
        with config.configure(device_cache_bytes=0, batch_size=104):
            profiles = ColumnProfiler.profile(data, cancel=token)
        assert profiles.interruption is not None
        # pass 1 discovered the dead envelope; passes 2/3 never ran
        assert tm.counter("runner.runs").value - runs_before == 1

    def test_float_deadline_becomes_shared_budget(self):
        from deequ_tpu.profiles.profiler import ColumnProfiler

        data = Dataset.from_pydict(_table_data())
        with config.configure(device_cache_bytes=0, batch_size=104):
            profiles = ColumnProfiler.profile(data, deadline=3600.0)
        # a generous deadline: profiled to completion, no interruption
        assert profiles.interruption is None
        assert profiles.num_records == 1000


# --------------------------------------------------------------------------
# Telemetry + obs_report rendering
# --------------------------------------------------------------------------


class TestSupervisionTelemetry:
    def test_obs_report_renders_supervision_section(self):
        from tools.obs_report import render_run

        summary = {
            "run_id": 7,
            "name": "supervised",
            "wall_s": 1.0,
            "counters": {
                "engine.stalls_detected": 2,
                "engine.runs_cancelled": 1,
                "engine.runs_queued": 3,
            },
            "events": [
                {"event": "scan_stalled", "stall_s": 1.0, "stalls": 2},
                {
                    "event": "run_cancelled",
                    "kind": "deadline",
                    "reason": "run deadline of 10s exhausted",
                    "batch_index": 5,
                    "row_offset": 520,
                    "checkpointed": True,
                },
            ],
        }
        text = render_run(summary)
        assert "engine.stalls_detected" in text
        assert "engine.runs_cancelled" in text
        assert "engine.runs_queued" in text
        assert "stall detected" in text
        assert "run interrupted (deadline)" in text

    def test_events_emitted_end_to_end(self):
        tm = get_telemetry()
        budget = _stall_budget(stall_s=1.0)
        with config.configure(
            device_cache_bytes=0, batch_size=104, scan_retry=FAST_RETRY
        ):
            with tm.run("supervision-report") as cap:
                ds = FaultInjectingDataset(
                    Dataset.from_pydict(_table_data()),
                    hang_at_batch={2: 99},
                    clock=budget.clock,
                )
                AnalysisRunner.do_analysis_run(
                    ds, ANALYZERS, engine=AnalysisEngine(budget=budget)
                )
        summary = cap.final
        assert summary["counters"].get("engine.stalls_detected", 0) >= 1
        assert any(
            e.get("event") == "scan_stalled"
            for e in summary.get("events", [])
        )
