"""Host-side 3VL oracle + random expression generator for the
predicate compiler (VERDICT r4 next #6: the repo's largest file was
guarded only by hand-written cases).

The oracle interprets the SAME AST the compiler consumes
(deequ_tpu.sql.predicate.parse_predicate) over plain Python row
values with documented SQL three-valued-logic semantics; the soak
compares its per-row compliance against the compiled device path on
random typed, null-ridden data. Shared parser = the differential
covers the COMPILER (LUT construction, code gathers, synthetic lanes,
3VL masks), which is where the 1.5k lines live.

Float columns are generated as f64 so host Python arithmetic and the
device's x64 lanes round identically; ints stay small so i32-narrowed
device arithmetic cannot overflow.

Importable pieces: ``oracle_compliance`` / ``gen_predicate`` /
``make_soak_dataset`` / ``run_predicate_soak`` (the CI smoke slice in
tests/test_predicate.py uses them with fixed seeds).
"""

from __future__ import annotations

import os
import sys
from typing import Optional

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

from deequ_tpu.sql.predicate import (
    Between,
    BinOp,
    BoolLit,
    CaseWhen,
    Cast,
    ColumnRef,
    FuncCall,
    InList,
    IsNull,
    Like,
    NullLit,
    NumberLit,
    StringLit,
    UnaryOp,
    _INT_CAST_BOUNDS,
    _INT_CASTS,
    _sql_like_to_regex,
    _STRING_CASTS,
    _substr,
    parse_predicate,
)

_NULL = object()  # SQL NULL marker distinct from Python None values


def _truth(v):
    """SQL truthiness of a non-null value (engine's _as_bool)."""
    if isinstance(v, bool):
        return v
    return v != 0


def _ev(node, row):
    """Evaluate to a Python value or _NULL (SQL NULL)."""
    import re

    if isinstance(node, ColumnRef):
        v = row[node.name]
        return _NULL if v is None else v
    if isinstance(node, NumberLit):
        return float(node.value)
    if isinstance(node, BoolLit):
        return node.value
    if isinstance(node, NullLit):
        return _NULL
    if isinstance(node, StringLit):
        return node.value
    if isinstance(node, UnaryOp):
        v = _ev(node.operand, row)
        if node.op == "NEG":
            return _NULL if v is _NULL else -v
        return _NULL if v is _NULL else (not _truth(v))
    if isinstance(node, IsNull):
        v = _ev(node.operand, row)
        return (v is _NULL) != node.negate
    if isinstance(node, Between):
        return _ev(
            BinOp(
                "AND",
                BinOp(">=", node.operand, node.low),
                BinOp("<=", node.operand, node.high),
            ),
            row,
        )
    if isinstance(node, Like):
        v = _ev(node.operand, row)
        if v is _NULL:
            return _NULL
        pattern = (
            node.pattern if node.regex else _sql_like_to_regex(node.pattern)
        )
        hit = re.search(pattern, str(v)) is not None
        return hit != node.negate
    if isinstance(node, InList):
        base = _ev(node.operand, row)
        if base is _NULL:
            return _NULL
        truth = False
        has_null_item = False
        for item in node.items:
            if isinstance(item, NullLit):
                has_null_item = True
                continue
            rhs = _ev(item, row)
            if rhs is _NULL:
                has_null_item = True
            elif _sql_eq(base, rhs):
                truth = True
        if not truth and has_null_item:
            return _NULL
        return truth != node.negate
    if isinstance(node, CaseWhen):
        for cond, result in node.whens:
            c = _ev(cond, row)
            if c is not _NULL and _truth(c):
                return _ev(result, row)
        return _ev(node.else_, row) if node.else_ is not None else _NULL
    if isinstance(node, Cast):
        v = _ev(node.operand, row)
        if node.type_name in _STRING_CASTS:
            if v is _NULL:
                return _NULL
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)
        integral = node.type_name in _INT_CASTS
        if v is _NULL:
            return _NULL
        if isinstance(v, str):
            text = v.strip()
            if "_" in text:
                return _NULL
            try:
                f = float(text)
            except ValueError:
                return _NULL
            if integral:
                if not np.isfinite(f):
                    return _NULL
                lo, hi = _INT_CAST_BOUNDS[node.type_name]
                return float(np.clip(np.trunc(f), lo, hi))
            return f
        f = float(v)
        if integral:
            lo, hi = _INT_CAST_BOUNDS[node.type_name]
            if np.isnan(f):
                return 0.0
            return float(np.clip(np.trunc(f), lo, hi))
        return f
    if isinstance(node, FuncCall):
        return _ev_func(node, row)
    if isinstance(node, BinOp):
        if node.op in ("AND", "OR"):
            lt = _ev(node.left, row)
            rt = _ev(node.right, row)
            lb = None if lt is _NULL else _truth(lt)
            rb = None if rt is _NULL else _truth(rt)
            if node.op == "AND":
                if lb is False or rb is False:
                    return False
                if lb is None or rb is None:
                    return _NULL
                return True
            if lb is True or rb is True:
                return True
            if lb is None or rb is None:
                return _NULL
            return False
        lv = _ev(node.left, row)
        rv = _ev(node.right, row)
        if lv is _NULL or rv is _NULL:
            return _NULL
        if node.op in ("=", "!=", "<", "<=", ">", ">="):
            return _sql_cmp(node.op, lv, rv)
        lv, rv = float(lv), float(rv)
        if node.op == "+":
            return lv + rv
        if node.op == "-":
            return lv - rv
        if node.op == "*":
            return lv * rv
        if node.op == "/":
            return _NULL if rv == 0 else lv / rv
        if node.op == "%":
            return _NULL if rv == 0 else lv % rv
    raise AssertionError(f"oracle cannot evaluate {node!r}")


def _sql_eq(a, b) -> bool:
    if isinstance(a, str) or isinstance(b, str):
        return isinstance(a, str) and isinstance(b, str) and a == b
    return float(a) == float(b)


def _sql_cmp(op, a, b):
    if isinstance(a, str) and isinstance(b, str):
        pass  # lexicographic
    else:
        a, b = float(a), float(b)
    return {
        "=": a == b,
        "!=": a != b,
        "<": a < b,
        "<=": a <= b,
        ">": a > b,
        ">=": a >= b,
    }[op]


def _ev_func(node, row):
    name = node.name
    if name == "ABS":
        v = _ev(node.args[0], row)
        return _NULL if v is _NULL else abs(float(v))
    if name == "LENGTH":
        v = _ev(node.args[0], row)
        return _NULL if v is _NULL else float(len(str(v)))
    if name == "COALESCE":
        for a in node.args:
            v = _ev(a, row)
            if v is not _NULL:
                return v
        return _NULL
    if name == "CONCAT":
        parts = []
        for a in node.args:
            v = _ev(a, row)
            if v is _NULL:
                return _NULL
            parts.append(str(v))
        return "".join(parts)
    if name in ("TRIM", "LTRIM", "RTRIM", "UPPER", "LOWER"):
        v = _ev(node.args[0], row)
        if v is _NULL:
            return _NULL
        return {
            "TRIM": str.strip,
            "LTRIM": str.lstrip,
            "RTRIM": str.rstrip,
            "UPPER": str.upper,
            "LOWER": str.lower,
        }[name](str(v))
    if name in ("SUBSTR", "SUBSTRING"):
        v = _ev(node.args[0], row)
        if v is _NULL:
            return _NULL
        pos = int(_ev(node.args[1], row))
        length = (
            int(_ev(node.args[2], row)) if len(node.args) == 3 else None
        )
        return _substr(str(v), pos, length)
    raise AssertionError(f"oracle does not model function {name}")


def oracle_compliance(expression: str, rows) -> float:
    """Fraction of rows on which the predicate is TRUE (SQL 3VL:
    NULL and FALSE both fail) — the Compliance analyzer's contract."""
    node = parse_predicate(expression)
    n = 0
    for row in rows:
        v = _ev(node, row)
        if v is not _NULL and _truth(v):
            n += 1
    return n / len(rows) if rows else 0.0


# --------------------------------------------------------------------------
# random generator
# --------------------------------------------------------------------------

_STR_POOL = ["aa", "b", "1.5", "Zq", "", "  pad  ", "NaN", "7", "x_y", "3000000000"]


def make_soak_dataset(rng, n: int = 200):
    """Typed columns with nulls/NaN/inf: f/g (f64), i/j (small ints),
    s/t (strings from a pool incl. numeric-ish entries), b (bool).
    Returns (Dataset, rows-as-dicts for the oracle)."""
    from deequ_tpu import Dataset

    f = rng.normal(0, 10, n)
    f[rng.random(n) < 0.1] = np.nan
    f[rng.random(n) < 0.05] = np.inf
    g = np.round(rng.normal(0, 5, n), 2)
    i = rng.integers(-100, 100, n)
    j = rng.integers(0, 10, n)
    s = np.array(_STR_POOL, dtype=object)[
        rng.integers(0, len(_STR_POOL), n)
    ]
    t = np.array(_STR_POOL, dtype=object)[
        rng.integers(0, len(_STR_POOL), n)
    ]
    b = rng.integers(0, 2, n) == 1

    def with_nulls(arr, p):
        arr = arr.astype(object)
        arr[rng.random(n) < p] = None
        return arr

    cols = {
        "f": with_nulls(f, 0.15),
        "g": g.astype(object),
        "i": with_nulls(i, 0.1),
        "j": j.astype(object),
        "s": with_nulls(s, 0.2),
        "t": t.astype(object),
        "b": with_nulls(b, 0.1),
    }
    ds = Dataset.from_pydict({k: list(v) for k, v in cols.items()})
    rows = [
        {k: cols[k][r] for k in cols} for r in range(n)
    ]
    return ds, rows


def gen_predicate(rng, depth: int = 3) -> str:
    return _gen_bool(rng, depth)


def _pick(rng, options):
    return options[rng.integers(0, len(options))]


def _gen_num(rng, depth: int) -> str:
    if depth <= 0 or rng.random() < 0.35:
        return _pick(
            rng,
            ["f", "g", "i", "j", "-2", "0", "3.5", "10"],
        )
    kind = rng.integers(0, 7)
    if kind == 0:
        op = _pick(rng, ["+", "-", "*", "/", "%"])
        return f"({_gen_num(rng, depth - 1)} {op} {_gen_num(rng, depth - 1)})"
    if kind == 1:
        return f"ABS({_gen_num(rng, depth - 1)})"
    if kind == 2:
        return f"LENGTH({_gen_str(rng, depth - 1)})"
    if kind == 3:
        target = _pick(rng, ["DOUBLE", "INT", "BIGINT", "SMALLINT"])
        return f"CAST({_gen_str(rng, depth - 1)} AS {target})"
    if kind == 4:
        target = _pick(rng, ["DOUBLE", "INT"])
        return f"CAST({_gen_num(rng, depth - 1)} AS {target})"
    if kind == 5:
        return (
            f"CASE WHEN {_gen_bool(rng, depth - 1)} THEN "
            f"{_gen_num(rng, depth - 1)} ELSE {_gen_num(rng, depth - 1)} END"
        )
    return f"COALESCE({_gen_num(rng, depth - 1)}, {_gen_num(rng, depth - 1)})"


def _gen_str(rng, depth: int) -> str:
    if depth <= 0 or rng.random() < 0.4:
        return _pick(rng, ["s", "t"])
    kind = rng.integers(0, 6)
    if kind == 0:
        fn = _pick(rng, ["TRIM", "UPPER", "LOWER"])
        return f"{fn}({_gen_str(rng, depth - 1)})"
    if kind == 1:
        pos = int(rng.integers(-3, 4))
        ln = int(rng.integers(1, 4))
        return f"SUBSTR({_gen_str(rng, depth - 1)}, {pos}, {ln})"
    if kind == 2:
        lit = _pick(rng, ["'-'", "''", "'Q'"])
        return (
            f"CONCAT({_gen_str(rng, depth - 1)}, {lit}, "
            f"{_gen_str(rng, depth - 1)})"
        )
    if kind == 3:
        return (
            f"CASE WHEN {_gen_bool(rng, depth - 1)} THEN "
            f"{_gen_str(rng, depth - 1)} ELSE {_gen_str(rng, depth - 1)} END"
        )
    if kind == 4:
        return (
            f"COALESCE({_gen_str(rng, depth - 1)}, "
            f"{_gen_str(rng, depth - 1)})"
        )
    return f"CAST({_gen_str(rng, depth - 1)} AS STRING)"


def _gen_bool(rng, depth: int) -> str:
    if depth <= 0:
        return _pick(rng, ["b", "f > 0", "i <= 3", "s = 'aa'"])
    kind = rng.integers(0, 9)
    if kind == 0:
        op = _pick(rng, ["=", "!=", "<", "<=", ">", ">="])
        return f"{_gen_num(rng, depth - 1)} {op} {_gen_num(rng, depth - 1)}"
    if kind == 1:
        op = _pick(rng, ["=", "!=", "<", ">="])
        lit = _pick(rng, ["'aa'", "'1.5'", "'Zq'", "''", "'qq'"])
        if rng.random() < 0.5:
            return f"{_gen_str(rng, depth - 1)} {op} {lit}"
        return f"{_gen_str(rng, depth - 1)} {op} {_gen_str(rng, depth - 1)}"
    if kind == 2:
        target = _pick(rng, ["f", "i", _gen_str(rng, depth - 1)])
        neg = _pick(rng, ["", "NOT "])
        return f"{target} IS {neg}NULL"
    if kind == 3:
        if rng.random() < 0.5:
            items = ", ".join(
                _pick(rng, ["1", "3.5", "-2", "0", "NULL"])
                for _ in range(int(rng.integers(1, 4)))
            )
            return f"{_gen_num(rng, depth - 1)} IN ({items})"
        items = ", ".join(
            _pick(rng, ["'aa'", "'7'", "'b'", "''"])
            for _ in range(int(rng.integers(1, 4)))
        )
        neg = _pick(rng, ["", "NOT "])
        return f"{_gen_str(rng, depth - 1)} {neg}IN ({items})"
    if kind == 4:
        pat = _pick(rng, ["'a%'", "'%7%'", "'_'", "'%pad%'"])
        neg = _pick(rng, ["", "NOT "])
        return f"{_gen_str(rng, depth - 1)} {neg}LIKE {pat}"
    if kind == 5:
        return (
            f"{_gen_num(rng, depth - 1)} BETWEEN "
            f"{_gen_num(rng, depth - 1)} AND {_gen_num(rng, depth - 1)}"
        )
    if kind == 6:
        op = _pick(rng, ["AND", "OR"])
        return (
            f"({_gen_bool(rng, depth - 1)} {op} "
            f"{_gen_bool(rng, depth - 1)})"
        )
    if kind == 7:
        return f"NOT ({_gen_bool(rng, depth - 1)})"
    return "b"


def run_predicate_soak(
    n_exprs: int, seed: int = 0, n_rows: int = 200, verbose: bool = True
):
    """Generate expressions, compare compiled vs oracle compliance.
    Returns (failures, skipped): a nonzero failure count means the
    compiler and the oracle disagree on some row's 3VL outcome."""
    from deequ_tpu.analyzers import AnalysisRunner, Compliance

    rng = np.random.default_rng(seed)
    ds, rows = make_soak_dataset(rng, n_rows)
    failures = []
    skipped = 0
    batch = []
    exprs = []
    for k in range(n_exprs):
        exprs.append(gen_predicate(rng, depth=int(rng.integers(2, 4))))
    # run in bundles: one fused scan amortizes dispatch
    chunk = 25
    for lo in range(0, len(exprs), chunk):
        sub = exprs[lo : lo + chunk]
        analyzers = [
            Compliance(f"p{lo + i}", e) for i, e in enumerate(sub)
        ]
        ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
        for a, e in zip(analyzers, sub):
            metric = ctx.metric(a)
            if not metric.value.is_success:
                skipped += 1  # plan-time rejection (over-budget etc.)
                continue
            got = metric.value.get()
            want = oracle_compliance(e, rows)
            if abs(got - want) > 1e-9:
                failures.append((e, got, want))
                if verbose:
                    print(f"MISMATCH {e!r}: device={got} oracle={want}")
    if verbose:
        print(
            f"predicate soak: {len(exprs)} exprs, "
            f"{len(failures)} mismatches, {skipped} plan-rejected"
        )
    return failures, skipped


# --------------------------------------------------------------------------
# boundary fuzz: grammar the planner must REJECT, cleanly
# --------------------------------------------------------------------------

# each template yields an expression OUTSIDE the supported grammar —
# unknown columns/functions, wrong arity, syntax junk, unsupported
# cast targets. "{num}"/"{str}"/"{bool}" splice in random VALID
# sub-expressions so the junk sits at realistic positions
_UNSUPPORTED_TEMPLATES = [
    "zz > {num}",  # unknown column
    "FOO({num}) > 0",  # unknown function
    "{num} >",  # dangling operator
    "{num} > > 0",  # doubled operator
    "({bool}",  # unbalanced paren
    "{num} BETWEEN {num}",  # BETWEEN without AND
    "{num} IN ()",  # empty IN list
    "CAST({num} AS BLOB) > 0",  # unsupported cast target
    "ABS({num}, {num}) > 0",  # wrong arity
    "SUBSTR({str}) = 'a'",  # missing SUBSTR position
    "{bool} AND",  # trailing conjunction
    "{str} ||| {str} = 'ab'",  # unknown operator
    "SELECT * FROM t",  # not a predicate at all
]


def gen_unsupported_predicate(rng) -> str:
    template = _pick(rng, _UNSUPPORTED_TEMPLATES)
    out = []
    rest = template
    while True:
        idx = min(
            (rest.find(m) for m in ("{num}", "{str}", "{bool}")
             if rest.find(m) >= 0),
            default=-1,
        )
        if idx < 0:
            out.append(rest)
            break
        out.append(rest[:idx])
        marker = rest[idx:idx + 6] if rest[idx:].startswith("{bool}") \
            else rest[idx:idx + 5]
        if marker == "{num}":
            out.append(_gen_num(rng, 1))
        elif marker == "{str}":
            out.append(_gen_str(rng, 1))
        else:
            out.append(_gen_bool(rng, 1))
        rest = rest[idx + len(marker):]
    return "".join(out)


def run_boundary_fuzz(
    n_exprs: int, seed: int = 0, n_rows: int = 50, verbose: bool = True
):
    """Feed deliberately-unsupported grammar through the FULL
    Compliance planning path. The contract is clean rejection: every
    expression ends as a plan-time failure metric — never a crash out
    of the runner, and (for the guaranteed-invalid templates) never a
    silent success. Returns (crashes, accepted)."""
    from deequ_tpu.analyzers import AnalysisRunner, Compliance

    rng = np.random.default_rng(seed)
    ds, _rows = make_soak_dataset(rng, n_rows)
    crashes = []
    accepted = []
    exprs = [gen_unsupported_predicate(rng) for _ in range(n_exprs)]
    chunk = 25
    for lo in range(0, len(exprs), chunk):
        sub = exprs[lo : lo + chunk]
        analyzers = [
            Compliance(f"u{lo + i}", e) for i, e in enumerate(sub)
        ]
        try:
            ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
        except Exception as exc:  # noqa: BLE001 — the defect we hunt
            crashes.append((sub, repr(exc)))
            continue
        for a, e in zip(analyzers, sub):
            if ctx.metric(a).value.is_success:
                accepted.append(e)
                if verbose:
                    print(f"ACCEPTED unsupported expr {e!r}")
    if verbose:
        print(
            f"boundary fuzz: {len(exprs)} exprs, "
            f"{len(crashes)} crashes, {len(accepted)} accepted"
        )
    return crashes, accepted


if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 400
    fails, _ = run_predicate_soak(n, seed=int(os.environ.get("SEED", 0)))
    crashes, _accepted = run_boundary_fuzz(
        max(50, n // 4), seed=int(os.environ.get("SEED", 0))
    )
    sys.exit(1 if (fails or crashes) else 0)
