"""Constraint-suggestion tests: each rule's fire/no-fire boundary plus
the runner's train/test holdout evaluation (reference test model:
ConstraintSuggestionRunnerTest + per-rule tests — SURVEY.md §4)."""

import numpy as np
import pytest

from deequ_tpu import Dataset
from deequ_tpu.checks.check import CheckStatus
from deequ_tpu.data.table import Kind
from deequ_tpu.metrics.distribution import Distribution, DistributionValue
from deequ_tpu.profiles.profiler import (
    NumericColumnProfile,
    StandardColumnProfile,
)
from deequ_tpu.suggestions.rules import (
    DEFAULT_RULES,
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    UniqueIfApproximatelyUniqueRule,
)
from deequ_tpu.suggestions.runner import ConstraintSuggestionRunner


def std_profile(**kwargs):
    base = dict(
        column="col",
        completeness=1.0,
        approximate_num_distinct_values=10.0,
        data_type=Kind.STRING,
        is_data_type_inferred=False,
        type_counts={},
        histogram=None,
    )
    base.update(kwargs)
    return StandardColumnProfile(**base)


def num_profile(**kwargs):
    base = dict(
        column="col",
        completeness=1.0,
        approximate_num_distinct_values=10.0,
        data_type=Kind.FRACTIONAL,
        is_data_type_inferred=False,
        type_counts={},
        histogram=None,
        mean=1.0,
        maximum=5.0,
        minimum=0.0,
        sum=10.0,
        std_dev=1.0,
    )
    base.update(kwargs)
    return NumericColumnProfile(**base)


def histogram(counts):
    total = sum(counts.values())
    return Distribution(
        {k: DistributionValue(v, v / total) for k, v in counts.items()},
        len(counts),
    )


class TestRuleBoundaries:
    def test_complete_if_complete(self):
        rule = CompleteIfCompleteRule()
        assert rule.should_be_applied(std_profile(completeness=1.0), 100)
        assert not rule.should_be_applied(std_profile(completeness=0.99), 100)
        s = rule.candidate(std_profile(), 100)
        assert ".is_complete" in s.code_for_constraint

    def test_retain_completeness_interval_math(self):
        rule = RetainCompletenessRule()
        assert rule.should_be_applied(std_profile(completeness=0.5), 100)
        assert rule.should_be_applied(std_profile(completeness=0.2), 100)
        assert not rule.should_be_applied(std_profile(completeness=0.19), 100)
        assert not rule.should_be_applied(std_profile(completeness=1.0), 100)
        # p=0.5, n=100: bound = 0.5 - 1.96*sqrt(0.25/100) = 0.402 -> 0.4
        s = rule.candidate(std_profile(completeness=0.5), 100)
        assert "0.4" in s.code_for_constraint

    def test_retain_type(self):
        rule = RetainTypeRule()
        fires = std_profile(
            is_data_type_inferred=True, data_type=Kind.INTEGRAL
        )
        assert rule.should_be_applied(fires, 10)
        assert not rule.should_be_applied(
            std_profile(is_data_type_inferred=True, data_type=Kind.STRING), 10
        )
        assert not rule.should_be_applied(
            std_profile(is_data_type_inferred=False, data_type=Kind.INTEGRAL),
            10,
        )
        assert "has_data_type" in rule.candidate(fires, 10).code_for_constraint

    def test_categorical_range(self):
        rule = CategoricalRangeRule()
        fires = std_profile(
            histogram=histogram({"a": 60, "b": 40}),
            approximate_num_distinct_values=2.0,
        )
        assert rule.should_be_applied(fires, 1000)
        # no histogram -> never
        assert not rule.should_be_applied(std_profile(), 1000)
        # high unique ratio -> no
        assert not rule.should_be_applied(
            std_profile(
                histogram=histogram({"a": 1, "b": 1}),
                approximate_num_distinct_values=500.0,
            ),
            1000,
        )
        s = rule.candidate(fires, 1000)
        assert '.is_contained_in("col", ["a", "b"])' in s.code_for_constraint

    def test_fractional_categorical_range(self):
        rule = FractionalCategoricalRangeRule()
        # two categories cover 98%, the tail is tiny -> fires
        fires = std_profile(
            histogram=histogram({"a": 600, "b": 380, "junk": 20})
        )
        assert rule.should_be_applied(fires, 1000)
        # coverage target only reached by using ALL values -> no
        assert not rule.should_be_applied(
            std_profile(histogram=histogram({"a": 50, "b": 50})), 100
        )
        s = rule.candidate(fires, 1000)
        assert "is_contained_in" in s.code_for_constraint
        assert "junk" not in s.code_for_constraint

    def test_non_negative_numbers(self):
        rule = NonNegativeNumbersRule()
        assert rule.should_be_applied(num_profile(minimum=0.0), 10)
        assert not rule.should_be_applied(num_profile(minimum=-0.1), 10)
        assert not rule.should_be_applied(std_profile(), 10)

    def test_unique_if_approximately_unique(self):
        rule = UniqueIfApproximatelyUniqueRule()
        assert rule.should_be_applied(
            std_profile(approximate_num_distinct_values=95.0), 100
        )
        assert not rule.should_be_applied(
            std_profile(approximate_num_distinct_values=80.0), 100
        )
        # incomplete columns are never suggested unique
        assert not rule.should_be_applied(
            std_profile(
                approximate_num_distinct_values=100.0, completeness=0.9
            ),
            100,
        )


class TestSuggestionRunner:
    @pytest.fixture(scope="class")
    def ds(self):
        n = 400
        rng = np.random.default_rng(7)
        return Dataset.from_pydict(
            {
                "id": list(range(n)),
                "cat": list(rng.choice(["x", "y", "z"], n)),
                "maybe": [
                    float(i) if i % 4 else None for i in range(n)
                ],
            }
        )

    def test_default_rules_produce_expected_suggestions(self, ds):
        result = (
            ConstraintSuggestionRunner()
            .on_data(ds)
            .add_constraint_rules(DEFAULT_RULES)
            .run()
        )
        by_rule = {
            s.suggesting_rule for s in result.all_suggestions()
        }
        assert "CompleteIfCompleteRule" in by_rule  # id, cat complete
        assert "UniqueIfApproximatelyUniqueRule" in by_rule  # id unique
        assert "CategoricalRangeRule" in by_rule  # cat low-card
        assert "RetainCompletenessRule" in by_rule  # maybe ~75%
        id_rules = {
            s.suggesting_rule
            for s in result.constraint_suggestions.get("id", [])
        }
        assert "NonNegativeNumbersRule" in id_rules

    def test_train_test_split_evaluates_holdout(self, ds):
        result = (
            ConstraintSuggestionRunner()
            .on_data(ds)
            .add_constraint_rules(DEFAULT_RULES)
            .use_train_test_split_with_testset_ratio(0.25)
            .run()
        )
        vr = result.verification_result
        assert vr is not None
        # structure holds on the holdout: all suggested constraints pass
        assert vr.status in (CheckStatus.SUCCESS, CheckStatus.WARNING)

    def test_rule_exception_does_not_kill_run(self, ds):
        class ExplodingRule(CompleteIfCompleteRule):
            def should_be_applied(self, profile, num_records):
                raise RuntimeError("boom")

        result = (
            ConstraintSuggestionRunner()
            .on_data(ds)
            .add_constraint_rule(ExplodingRule())
            .add_constraint_rule(CompleteIfCompleteRule())
            .run()
        )
        assert result.all_suggestions()  # the healthy rule still ran
