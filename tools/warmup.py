"""Precompile deequ_tpu's fused plans for a schema, ahead of data.

First-EVER XLA:TPU compilation of a big fused profiler plan costs
~110 s (20-col plan; docs/PERF.md pool 3). The persistent cache
(``DEEQU_TPU_COMPILE_CACHE``, default ``~/.cache/deequ_tpu_xla``)
makes it one-time per machine — but without this tool, the FIRST
production run eats it in full. Run warmup at deploy time instead:

    python tools/warmup.py --like-parquet /path/to/table.parquet
    python tools/warmup.py --schema '{"price": "float32", "id": "int64",
                                      "cat": "string"}'

and the first production run's compiles become ~0.1-2 s cache
deserializations (measured; docs/PERF.md).

What gets compiled is keyed by (analyzer structure, schema kinds,
batch shape, wire dtypes) — NOT by data values (dictionaries/LUTs ride
as runtime inputs). The synthetic warm data therefore only has to hit
the same STATIC decisions production data will:

- batch size (``--batch-size``, default = the engine default);
- per-column wire dtype: int64 columns whose values all fit int32
  ship narrowed, so ``--int-width`` picks which program to warm
  (``both`` warms the two variants);
- null presence: an all-valid column's mask is synthesized on device
  (a DIFFERENT program than a shipped mask), so ``--nullable both``
  (default) warms both.

``--suite`` additionally warms a VerificationSuite-shaped plan
(completeness/uniqueness/compliance per column) on top of the default
ColumnProfiler plan.

Two engine options are part of the plan fingerprint (r6) and get their
own warm pass automatically when they would change the compiled
program: ``pallas_scatter`` (the plan-cache key carries the resolved
impl token, so the Pallas-scatter program is distinct — warmed only
where the kernel is actually available, i.e. on a TPU host) and
``hll_dedup_widening`` (off compiles the scatter-only pooled HLL unit
instead of the runtime-gated ``lax.cond`` unit — warmed whenever the
schema has an int column, so a production flag-flip never eats a
compile).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402

_KINDS = (
    "float32", "float64", "int32", "int64", "string", "bool", "timestamp"
)


def _schema_from_parquet(path: str):
    import pyarrow.dataset as pads
    import pyarrow as pa

    schema = pads.dataset(path, format="parquet").schema
    out = {}
    for name, typ in zip(schema.names, schema.types):
        if pa.types.is_dictionary(typ):
            typ = typ.value_type
        if pa.types.is_floating(typ):
            out[name] = "float32" if typ.bit_width == 32 else "float64"
        elif pa.types.is_boolean(typ):
            out[name] = "bool"
        elif pa.types.is_integer(typ):
            out[name] = "int32" if typ.bit_width <= 32 else "int64"
        elif pa.types.is_string(typ) or pa.types.is_large_string(typ):
            out[name] = "string"
        elif pa.types.is_timestamp(typ) or pa.types.is_date(typ):
            out[name] = "timestamp"
        else:
            print(f"  (skipping unsupported column {name}: {typ})")
    return out


def synthetic_dataset(schema, rows: int, nullable: bool, wide_ints: bool,
                      seed: int = 0, high_card_strings: bool = False):
    """A dataset matching the schema's STATIC compile decisions.
    ``high_card_strings`` warms the i32-codes / no-histogram program
    (dictionary-code wire width and the profiler's low-cardinality
    histogram gate are both static per column)."""
    import pyarrow as pa

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(seed)
    cols = {}
    null_mask = (
        (rng.random(rows) < 0.05) if nullable else np.zeros(rows, bool)
    )
    for name, kind in schema.items():
        if kind in ("float32", "float64"):
            vals = rng.normal(0.0, 1.0, rows).astype(kind)
            arr = pa.array(vals, mask=null_mask if nullable else None)
        elif kind in ("int32", "int64"):
            hi = (1 << 40) if (wide_ints and kind == "int64") else 1 << 20
            vals = rng.integers(0, hi, rows).astype(kind)
            arr = pa.array(vals, mask=null_mask if nullable else None)
        elif kind == "bool":
            arr = pa.array(
                rng.random(rows) < 0.5,
                mask=null_mask if nullable else None,
            )
        elif kind == "timestamp":
            base = np.datetime64("2024-01-01", "us")
            vals = base + rng.integers(0, 1 << 40, rows).astype(
                "timedelta64[us]"
            )
            arr = pa.array(vals, pa.timestamp("us"),
                           mask=null_mask if nullable else None)
        elif kind == "string":
            # 64 distinct -> i8 codes + the profiler's histogram pass;
            # 200k distinct -> i32 codes, histogram gate off
            n_cats = min(200_000, max(rows, 2)) if high_card_strings else 64
            cats = np.array([f"w{j:06d}" for j in range(n_cats)])
            vals = cats[rng.integers(0, len(cats), rows)]
            arr = pa.array(
                vals, mask=null_mask if nullable else None
            ).dictionary_encode()
        else:
            raise ValueError(f"unknown kind {kind!r} (use one of {_KINDS})")
        cols[name] = arr
    return Dataset.from_arrow(pa.table(cols))


def warm_once(schema, rows, nullable, wide_ints, suite: bool,
              high_card_strings: bool = False, checks=None,
              profile: bool = True, engine=None) -> float:
    """One warm pass: the ColumnProfiler plan (unless ``profile=False``)
    plus a VerificationSuite plan — either the EXACT production
    ``checks`` (the service warms the suites it will actually serve) or
    a synthesized schema-shaped check when ``suite=True``. ``engine``
    pins a specific ``AnalysisEngine`` (e.g. a mesh over an elastic
    device slice) so the pass warms THAT placement shape's plan."""
    ds = synthetic_dataset(
        schema, rows, nullable, wide_ints,
        high_card_strings=high_card_strings,
    )
    t0 = time.time()
    if profile:
        from deequ_tpu.profiles.profiler import ColumnProfiler

        ColumnProfiler.profile(ds, engine=engine)
    if checks is not None:
        from deequ_tpu import VerificationSuite

        # compiles key on structure/shapes/dtypes, never values — a
        # synthetic dataset with the production schema warms the
        # production suite's plan exactly
        VerificationSuite().on_data(ds).add_checks(
            list(checks)
        ).with_engine(engine).run()
    elif suite:
        from deequ_tpu import Check, CheckLevel, VerificationSuite

        check = Check(CheckLevel.ERROR, "warmup")
        for name, kind in schema.items():
            check = check.is_complete(name)
            if kind in ("float32", "float64", "int32", "int64"):
                check = check.is_non_negative(name)
            if kind in ("int32", "int64", "string"):
                check = check.is_unique(name)
        # the profiler's dataset warms the suite plan equally well
        VerificationSuite().on_data(ds).add_check(check).with_engine(
            engine
        ).run()
    return time.time() - t0


def default_engine_variants(schema) -> list:
    """Engine-option variants that change the compiled program for
    this schema on THIS host (each is a distinct plan-cache
    fingerprint; see engine/scan.py ``_plan_cache_key``). The default
    pass warms (xla scatter, widening on); extra passes only run when
    they would actually compile something different."""
    from deequ_tpu import config
    from deequ_tpu.sketches import pallas_scatter

    variants = [{}]
    if any(k in ("int32", "int64") for k in schema.values()):
        # dedup-gate branch: widening off is the scatter-only pooled
        # HLL unit — warm it so flipping the escape hatch in
        # production is free
        variants.append({"hll_dedup_widening": False})
    with config.configure(pallas_scatter=True):
        if pallas_scatter.impl_token() == "pallas":
            variants.append({"pallas_scatter": True})
    # streaming wire, codecs on AND off: the codec-table token rides
    # the streaming plan fingerprint (engine/scan.py), so the codec-on
    # wire and the codecs-off differential oracle are two distinct
    # plans — warm both with the device cache off (the resident passes
    # above never build a wire). The probe-resolved codec table for
    # the synthetic data matches production only as far as the
    # synthetic value ranges do (wide_ints covers both int widths).
    variants.append({"device_cache_bytes": 0})
    variants.append({"device_cache_bytes": 0, "wire_codecs": False})
    # NO variants for the r10 ingest knobs (ingest_workers /
    # ingest_depth / ingest_lookahead / process_sharded_ingest): they
    # are host-pipeline concurrency settings read inside
    # _run_scan_streaming AFTER prepare_scan, so they are
    # plan-fingerprint-neutral by construction (the staticcheck
    # `plankey` gate enforces this) — every worker count reuses the
    # same warmed plan.
    return variants


def _mesh_engines(mesh_shapes):
    """(label, engine-or-None) per requested placement shape. ``None``
    in ``mesh_shapes`` warms the default (host/whole-backend) engine; an
    integer ``n`` warms an n-device ``Mesh`` — the SAME shape-keyed plan
    entry (engine/scan.py ``_placement_shape``) the elastic placer's
    n-device slices execute, whichever concrete devices the pool hands
    out. Shapes exceeding the host's device count are skipped (warming
    a shape the pool can never grant is dead work)."""
    engines = []
    for shape in mesh_shapes:
        if shape is None:
            engines.append(("default", None))
            continue
        import jax
        from jax.sharding import Mesh

        from deequ_tpu.engine.scan import AnalysisEngine

        devices = jax.devices()
        n = int(shape)
        if n < 1 or n > len(devices):
            continue
        mesh = Mesh(np.array(devices[:n]), ("dp",))
        engines.append((f"mesh{n}", AnalysisEngine(mesh=mesh)))
    return engines


def warm_plans(
    schema,
    suite: bool = False,
    batch_size=None,
    nullable=(False, True),
    wide_ints=None,
    high_card_strings=(False,),
    engine_variants=None,
    checks=None,
    profile: bool = True,
    mesh_shapes=(None,),
    log=None,
) -> dict:
    """Warm every fused-plan variant for ``schema`` and REPORT what got
    warmed — the reusable core behind both the CLI and the
    verification service's startup warmup (deequ_tpu/service).

    ``mesh_shapes`` extends the sweep across placement shapes: each
    entry is ``None`` (the default engine) or a device count ``n`` (an
    n-device mesh — the shape an elastic n-device slice executes).

    Returns ``{"tokens": [...], "already_warm": int, "passes": int,
    "total_s": float}`` where ``tokens`` are the structural plan-cache
    tokens (engine/scan.py ``plan_cache_snapshot``) ADDED by this call
    — the currency the service's PlanCache ledger tracks."""
    from deequ_tpu import config
    from deequ_tpu.engine.scan import DEFAULT_MAX_BATCH, plan_cache_snapshot

    batch = (
        batch_size or config.options().batch_size or DEFAULT_MAX_BATCH
    )
    # ONE batch of warm rows: compiles are shape-keyed, so more adds
    # nothing; engines resolve batch_size = min(rows, default), so the
    # warm row count must equal the production batch size exactly
    rows = batch
    has_int64 = any(k == "int64" for k in schema.values())
    has_string = any(k == "string" for k in schema.values())
    if wide_ints is None:
        wide_ints = (False, True) if has_int64 else (False,)
    if not has_string:
        high_card_strings = (False,)
    if engine_variants is None:
        engine_variants = default_engine_variants(schema)

    engines = _mesh_engines(mesh_shapes)
    before = set(plan_cache_snapshot())
    total = 0.0
    passes = 0
    for variant in engine_variants:
        tag = (
            " ".join(f"{k}={v}" for k, v in variant.items()) or "default"
        )
        with config.configure(batch_size=batch, **variant):
            for shape_tag, engine in engines:
                for null in nullable:
                    for wide in wide_ints:
                        for high_card in high_card_strings:
                            t = warm_once(
                                schema, rows, null, wide, suite,
                                high_card_strings=high_card,
                                checks=checks, profile=profile,
                                engine=engine,
                            )
                            total += t
                            passes += 1
                            if log is not None:
                                log(
                                    f"  warmed [{tag}/{shape_tag}] "
                                    f"nullable={null} "
                                    f"wide_ints={wide} "
                                    f"high_card_strings={high_card}: "
                                    f"{t:.1f}s"
                                )
    after = plan_cache_snapshot()
    tokens = [t for t in after if t not in before]
    return {
        "tokens": tokens,
        "already_warm": len(before & set(after)),
        "passes": passes,
        "total_s": total,
    }


def main() -> int:
    parser = argparse.ArgumentParser(
        description="precompile deequ_tpu plans for a schema"
    )
    parser.add_argument("--schema", help="JSON {column: kind}")
    parser.add_argument(
        "--like-parquet", help="read the schema from a parquet file/dir"
    )
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument(
        "--nullable", choices=("none", "all", "both"), default="both"
    )
    parser.add_argument(
        "--int-width", choices=("narrow", "wide", "both"), default="both"
    )
    parser.add_argument(
        "--string-cardinality",
        choices=("low", "high", "both"),
        default="low",
        help="low: i8 codes + histogram pass; high: i32 codes, no "
        "histogram (two different compiled programs)",
    )
    parser.add_argument(
        "--suite", action="store_true",
        help="also warm a VerificationSuite-shaped plan",
    )
    parser.add_argument(
        "--mesh-shapes", default=None,
        help="comma-separated device counts to warm as mesh placement "
        "shapes (e.g. '1,2,4' for an elastic-placement service); "
        "'default' entries warm the host engine",
    )
    args = parser.parse_args()

    if bool(args.schema) == bool(args.like_parquet):
        parser.error("exactly one of --schema / --like-parquet")
    schema = (
        json.loads(args.schema)
        if args.schema
        else _schema_from_parquet(args.like_parquet)
    )
    if not schema:
        parser.error(
            "schema is empty (no supported columns) — nothing to warm"
        )
    for kind in schema.values():
        if kind not in _KINDS:
            parser.error(f"unknown kind {kind!r} (use one of {_KINDS})")
    print(f"schema: {schema}")

    from deequ_tpu import config

    nullables = {
        "none": (False,), "all": (True,), "both": (False, True)
    }[args.nullable]
    widths = {
        "narrow": (False,), "wide": (True,), "both": (False, True)
    }[args.int_width]
    cards = {
        "low": (False,), "high": (True,), "both": (False, True)
    }[args.string_cardinality]
    has_int64 = any(k == "int64" for k in schema.values())

    mesh_shapes = (None,)
    if args.mesh_shapes:
        mesh_shapes = tuple(
            None if part.strip() == "default" else int(part)
            for part in args.mesh_shapes.split(",")
            if part.strip()
        )

    report = warm_plans(
        schema,
        suite=args.suite,
        batch_size=args.batch_size,
        nullable=nullables,
        wide_ints=widths if has_int64 else (False,),
        high_card_strings=cards,
        mesh_shapes=mesh_shapes,
        log=print,
    )
    tokens = ", ".join(report["tokens"]) or "(all already resident)"
    print(f"warmed plan tokens: {tokens}")
    print(
        f"done in {report['total_s']:.1f}s ({report['passes']} passes) "
        f"— plans persisted to "
        f"{config.options().compilation_cache_dir}; the first "
        "production run now deserializes instead of compiling"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
