"""Streaming parquet ingest: multi-file sources feed the fused scan
batch-by-batch with bounded host memory and results identical to the
in-memory path (VERDICT.md next-round #3; SURVEY.md §7 stage 0)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    Dataset,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    config,
)
from deequ_tpu.analyzers import AnalysisRunner
from deequ_tpu.engine import AnalysisEngine


@pytest.fixture(scope="module")
def parquet_dir(tmp_path_factory):
    """Three parquet files with numeric, nullable, and string columns."""
    directory = tmp_path_factory.mktemp("pq")
    rng = np.random.default_rng(5)
    tables = []
    for i in range(3):
        n = 1000 + i * 500
        x = rng.normal(10.0, 2.0, n)
        x_arr = pa.array(x, pa.float64(), mask=(rng.random(n) < 0.1))
        tables.append(
            pa.table(
                {
                    "x": x_arr,
                    "k": pa.array(rng.integers(0, 1 << 40, n)),
                    "s": pa.array(
                        rng.choice(["red", "green", "blue", "mail@x.io"], n)
                    ),
                }
            )
        )
        pq.write_table(tables[-1], os.path.join(directory, f"part-{i}.parquet"))
    full = pa.concat_tables(tables)
    return str(directory), full


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Sum("x"),
    Minimum("x"),
    Maximum("x"),
    StandardDeviation("x"),
    Compliance("big x", "x > 10"),
    ApproxCountDistinct("k"),
    PatternMatch("s", r"@"),
    Histogram("s"),
]


def metrics_of(ctx):
    out = {}
    for a in ANALYZERS:
        m = ctx.metric(a)
        if m.value.is_success and not hasattr(m.value.get(), "values"):
            out[repr(a)] = m.value.get()
    return out


class TestParquetStreaming:
    def test_matches_in_memory_results(self, parquet_dir):
        directory, full = parquet_dir
        streamed = Dataset.from_parquet(directory)
        in_memory = Dataset.from_arrow(full)
        assert streamed.num_rows == full.num_rows
        ctx_stream = AnalysisRunner.do_analysis_run(streamed, ANALYZERS)
        ctx_memory = AnalysisRunner.do_analysis_run(in_memory, ANALYZERS)
        want, got = metrics_of(ctx_memory), metrics_of(ctx_stream)
        assert set(want) == set(got)
        for k in want:
            assert got[k] == pytest.approx(want[k], rel=1e-9), k
        # histogram too (string global dictionary must be stable)
        h_stream = ctx_stream.metric(Histogram("s")).value.get()
        h_memory = ctx_memory.metric(Histogram("s")).value.get()
        assert {k: v.absolute for k, v in h_stream.values.items()} == {
            k: v.absolute for k, v in h_memory.values.items()
        }

    def test_streaming_path_never_materializes_columns(self, parquet_dir):
        """With the device cache disabled, the engine must stream: no
        full-column host materialization happens."""
        directory, _ = parquet_dir
        streamed = Dataset.from_parquet(directory, read_batch_rows=512)
        with config.configure(device_cache_bytes=0):
            engine = AnalysisEngine(batch_size=700)
            ctx = AnalysisRunner.do_analysis_run(
                streamed, [Mean("x"), Size()], engine=engine
            )
        assert ctx.metric(Size()).value.get() == streamed.num_rows
        # materialize() caches full columns; the streaming path bypasses it
        assert not streamed._materialized
        assert engine.trace_count == 1 or engine.plan_cache_hit

    def test_small_read_batches_rechunk_correctly(self, parquet_dir):
        directory, full = parquet_dir
        streamed = Dataset.from_parquet(directory, read_batch_rows=333)
        with config.configure(device_cache_bytes=0):
            engine = AnalysisEngine(batch_size=1000)
            ctx = AnalysisRunner.do_analysis_run(
                streamed, [Size(), Sum("x")], engine=engine
            )
        in_memory = Dataset.from_arrow(full)
        want = AnalysisRunner.do_analysis_run(in_memory, [Sum("x")])
        assert ctx.metric(Sum("x")).value.get() == pytest.approx(
            want.metric(Sum("x")).value.get(), rel=1e-9
        )

    def test_resident_path_also_works(self, parquet_dir):
        """Under the budget, the resident fast path materializes from
        parquet and still matches."""
        directory, full = parquet_dir
        streamed = Dataset.from_parquet(directory)
        ctx = AnalysisRunner.do_analysis_run(streamed, [Mean("x")])
        want = AnalysisRunner.do_analysis_run(
            Dataset.from_arrow(full), [Mean("x")]
        )
        assert ctx.metric(Mean("x")).value.get() == pytest.approx(
            want.metric(Mean("x")).value.get(), rel=1e-9
        )

    def test_single_file_and_metadata(self, parquet_dir):
        directory, full = parquet_dir
        one = Dataset.from_parquet(os.path.join(directory, "part-0.parquet"))
        assert one.num_rows == 1000
        assert one.num_columns == 3
        assert one.schema.kind_of("x").is_numeric

    def test_streaming_plan_cache_reuse(self, parquet_dir):
        """A second streamed run of the SAME plan reuses the cached
        jitted update: no Python retrace (r4: the streaming path joined
        the plan cache; before, every profile retraced ~100 analyzers)."""
        directory, _ = parquet_dir
        plan = [Size(), Mean("x"), Completeness("x")]
        with config.configure(device_cache_bytes=0):
            first = AnalysisEngine(batch_size=1000)
            AnalysisRunner.do_analysis_run(
                Dataset.from_parquet(directory), plan, engine=first
            )
            second = AnalysisEngine(batch_size=1000)
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_parquet(directory), plan, engine=second
            )
        assert second.plan_cache_hit
        assert second.trace_count == 0
        assert ctx.metric(Size()).value.is_success

    def test_streaming_phase_decomposition_recorded(self, parquet_dir):
        """Every scan records its wall decomposition (host_wait / put /
        dispatch / sync) as a scan_phases event (VERDICT r3 next #2)."""
        directory, _ = parquet_dir
        with config.configure(device_cache_bytes=0):
            engine = AnalysisEngine(batch_size=1000)
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_parquet(directory), [Mean("x")], engine=engine
            )
        events = [
            e
            for e in ctx.run_metadata.events
            if e.get("event") == "scan_phases"
        ]
        assert len(events) == 1
        phases = events[0]
        assert phases["mode"] == "streaming"
        for key in ("host_wait_s", "put_s", "dispatch_s", "sync_s"):
            assert phases[key] >= 0.0
        # resident runs record the same decomposition
        ctx2 = AnalysisRunner.do_analysis_run(
            Dataset.from_parquet(directory), [Mean("x")]
        )
        modes = [
            e["mode"]
            for e in ctx2.run_metadata.events
            if e.get("event") == "scan_phases"
        ]
        assert modes == ["resident"]
