"""Grouping (frequency-based) analyzers: CountDistinct, Distinctness,
Uniqueness, UniqueValueRatio, Entropy, MutualInformation, Histogram.

Reference: ``src/main/scala/com/amazon/deequ/analyzers/GroupingAnalyzers.scala``
and one file per analyzer (SURVEY.md §2.2): analyzers over value
frequencies share one ``groupBy().count()`` per distinct (grouping
columns, filter) — the shared state is ``FrequenciesAndNumRows``.

TPU design (SURVEY.md §7 hard part #1): the TPU has no shuffle. Grouping
columns are dictionary-encoded host-side by Arrow's C++ kernels (exact,
vectorized); the device pass is a masked scatter-add of joint codes into
a dense count vector — one fused pass per frequency group, batched the
same way as the scan analyzers. Cross-shard/cross-dataset merges operate
on (key, count) pairs host-side, exactly like the reference merges
frequency DataFrames with unionByName + groupBy.sum (SURVEY.md §3.2).
For joint-key spaces too large for a dense vector, computation falls
back to Arrow's multithreaded host group_by.

Row semantics follow the reference: rows where ALL grouping columns are
null are excluded (``atLeastOneNonNullGroupingColumn``); Histogram runs
its own frequency pass that keeps nulls as a ``NullValue`` bin.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from deequ_tpu.analyzers.base import (
    Analyzer,
    EmptyStateException,
    GroupingAnalyzer,
    MetricCalculationException,
    Precondition,
    has_column,
)
from deequ_tpu.data.table import ROW_MASK, ColumnRequest, Dataset
from deequ_tpu.engine.memory import (
    classify_memory_pressure,
    oom_probe_of,
    record_spill_downgrade,
)
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.metrics.distribution import HistogramMetric
from deequ_tpu.metrics.metric import DoubleMetric, Entity, Metric
from deequ_tpu.sql.predicate import compile_predicate

NULL_VALUE = "NullValue"  # reference: Histogram's bin name for nulls
MAX_DENSE_JOINT = 1 << 24  # dense cap floor when no budget is configured


def _padded_dense_len(joint: int) -> int:
    """Pow2 length of the dense count vector: 1 << bit_length(joint) is
    strictly greater than joint, so the overflow slot always fits."""
    return 1 << max(1, int(joint).bit_length())


def _dense_joint_cap(num_rows: int) -> Tuple[int, "np.dtype"]:
    """(max COMBINED joint key space, count dtype) for the dense device
    path. The cap follows the configured grouping budget exactly (a
    small budget on a memory-constrained device must be honored); count
    vectors are i32 when every per-key count provably fits
    (num_rows < 2^31), which doubles the affordable key space
    (~2^28 keys per GB)."""
    from deequ_tpu import config

    budget = config.options().dense_grouping_budget_bytes
    dtype = np.int32 if num_rows < 2**31 else np.int64
    if not budget:
        return MAX_DENSE_JOINT, dtype
    return max(1, budget // np.dtype(dtype).itemsize), dtype


# --------------------------------------------------------------------------
# Shared state
# --------------------------------------------------------------------------


class FrequenciesAndNumRows:
    """(value combination -> count) plus the number of contributing rows.

    Host-side object (the reference's equivalent holds a DataFrame):
    ``keys`` is an object ndarray of shape (K, n_cols) whose entries are
    Python values (None encodes SQL NULL), ``counts`` an int64 (K,).
    Merge is a host dictionary union with summed counts — the incremental
    path across datasets/days (SURVEY.md §3.2).
    """

    def __init__(
        self,
        columns: Tuple[str, ...],
        keys: Optional[np.ndarray],
        counts: np.ndarray,
        num_rows: int,
        lazy_codes: Optional[Tuple] = None,
    ):
        """``keys`` may be None with ``lazy_codes=(observed_codes,
        dictionaries, sizes)``: count-only metrics (Uniqueness,
        Distinctness, CountDistinct) never touch key VALUES, and
        decoding 10M joint codes into object arrays costs seconds —
        so decoding happens on first ``.keys`` access only."""
        self.columns = tuple(columns)
        self._keys = keys
        self._lazy = lazy_codes
        self.counts = np.asarray(counts, dtype=np.int64)
        self.num_rows = int(num_rows)

    @property
    def keys(self) -> np.ndarray:
        if self._keys is None:
            observed, dictionaries, sizes = self._lazy
            self._keys = _decode_joint_codes(
                len(self.columns), observed, dictionaries, sizes
            )
        return self._keys

    def non_null_group_mask(self) -> np.ndarray:
        """True where NO key column is null — computable straight from
        the joint codes (slot 0 = null) without decoding values."""
        if self._lazy is not None:
            observed, _, sizes = self._lazy
            remaining = observed.copy()
            mask = np.ones(len(observed), dtype=bool)
            for j in range(len(self.columns) - 1, -1, -1):
                slot = remaining % sizes[j]
                remaining = remaining // sizes[j]
                mask &= slot > 0
            return mask
        # eager keys (spill-path states can hold 100M+ groups): a
        # vectorized object comparison, not a per-row Python loop
        return ~np.equal(self.keys, None).any(axis=1)

    @property
    def num_groups(self) -> int:
        return len(self.counts)

    # -- metric fast paths (DeviceFrequencies overrides these with
    #    on-device scalars so huge group sets never cross the wire) ----

    def count_unique_groups(self) -> int:
        """#groups occurring exactly once (Uniqueness/UniqueValueRatio)."""
        return int(np.sum(self.counts == 1))

    def entropy_nats(self) -> float:
        """Shannon entropy of the non-null group distribution."""
        counts = self.counts[self.non_null_group_mask()].astype(np.float64)
        total = counts.sum()
        if total == 0:
            raise EmptyStateException("Entropy over empty distribution.")
        p = counts / total
        return float(-(p * np.log(p)).sum())

    def top_groups(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """(first-column key values, counts) of the k most frequent
        groups, count-descending (Histogram's detail bins).

        Tie-break divergence (documented, ADVICE r3): among groups with
        EQUAL counts at the k-boundary, this path keeps first-seen
        order (stable argsort) while the device spill path keeps
        ascending packed-key order (lax.top_k over sorted segments) —
        the same data can select different boundary bins depending on
        which path ran. Counts, ratios, and every derived metric are
        identical; only WHICH of the equal-count bins beyond the cap
        survive differs. A canonical cross-path tie order would need a
        type-aware secondary sort (numeric vs code vs lexicographic)
        on both paths for marginal value; callers needing stability
        should raise max_detail_bins above the distinct count."""
        order = np.argsort(-self.counts, kind="stable")[:k]
        return self.keys[order, 0], self.counts[order]

    @staticmethod
    def merge(
        a: "FrequenciesAndNumRows", b: "FrequenciesAndNumRows"
    ) -> "FrequenciesAndNumRows":
        """Vectorized union+sum via Arrow's multithreaded group_by — the
        reference merges frequency DataFrames with unionByName +
        groupBy.sum (SURVEY.md §3.2); a Python dict loop here would crawl
        on multi-million-key states."""
        if a.columns != b.columns:
            raise ValueError(
                f"cannot merge frequencies over {a.columns} with {b.columns}"
            )
        columns = list(a.columns)
        if a.num_groups == 0 and b.num_groups == 0:
            return FrequenciesAndNumRows(
                a.columns,
                np.empty((0, len(columns)), dtype=object),
                np.zeros(0, dtype=np.int64),
                a.num_rows + b.num_rows,
            )
        count_col = _free_column_name(columns)
        data = {}
        for j, c in enumerate(columns):
            data[c] = pa.array(
                np.concatenate([a.keys[:, j], b.keys[:, j]]).tolist()
            )
        data[count_col] = pa.array(
            np.concatenate([a.counts, b.counts]), pa.int64()
        )
        grouped = (
            pa.table(data).group_by(columns).aggregate([(count_col, "sum")])
        )
        return _grouped_to_frequencies(
            grouped,
            columns,
            f"{count_col}_sum",
            a.num_rows + b.num_rows,
        )


# --------------------------------------------------------------------------
# Frequency computation (the "groupBy" pass)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class FrequencyPlan:
    """Identity of one shared frequency pass."""

    columns: Tuple[str, ...]
    where: Optional[str]
    include_nulls: bool  # Histogram keeps nulls as their own bin


def compute_frequencies(
    dataset: Dataset,
    plan: FrequencyPlan,
    engine: Optional[AnalysisEngine] = None,
) -> FrequenciesAndNumRows:
    return compute_many_frequencies(dataset, [plan], engine)[plan]


def plan_frequency_passes(
    dataset: Dataset,
    plans: Sequence[FrequencyPlan],
    engine: Optional[AnalysisEngine] = None,
    events: Optional[List[dict]] = None,
):
    """Split frequency plans into execution strategies WITHOUT running
    anything yet, so dense plans can ride the caller's shared scan:

    returns ``(dense_specs, collectors, deferred)`` where
    - ``dense_specs`` is a list of ``(plan, dictionaries, sizes,
      requests, ops)`` — ScanOps for the shared fused scan, finalized
      via :func:`finalize_dense_states`;
    - ``collectors`` is a list of :class:`spill.CollectorSpec` — spill
      plans whose u64 key extraction ALSO rides the shared fused scan
      (one-pass spill), finalized via
      :func:`finalize_collector_states`. Empty when
      ``config.options().one_pass_spill`` is off;
    - ``deferred`` maps plan -> zero-arg callable running the
      per-plan deferred re-scan spill (analyzers/spill.py) or the
      host Arrow fallback. Spill decisions are recorded in ``events``
      so a 100x-slower high-card pass is visible in run metadata
      instead of silent (VERDICT r2 weak #8)."""
    from deequ_tpu import config
    from deequ_tpu.analyzers import spill as spill_mod

    engine = engine or AnalysisEngine()
    use_collectors = config.options().one_pass_spill
    collectors: List = []
    cap, count_dtype = _dense_joint_cap(dataset.num_rows)
    dense: List[Tuple] = []
    deferred: Dict[FrequencyPlan, object] = {}
    # the cap bounds the COMBINED key space: all dense plans ride one
    # fused scan, so their count vectors are live on device together
    remaining = cap

    def note(plan, path):
        # dual-write: the telemetry event feeds run captures/listeners/
        # JSONL; the legacy ``events`` list keeps disabled-telemetry
        # callers (and explicitly-passed metadata) intact
        from deequ_tpu.telemetry import get_telemetry

        tm = get_telemetry()
        tm.counter(f"grouping.spill.{path}").inc()
        tm.event(
            "grouping_spill", columns=list(plan.columns), path=path
        )
        if events is not None:
            events.append(
                {
                    "event": "grouping_spill",
                    "columns": list(plan.columns),
                    "path": path,
                }
            )

    def make_spill(plan):
        def run():
            probe = oom_probe_of(dataset)
            try:
                if probe is not None:
                    probe("deferred")
                result = spill_mod.device_spill_frequencies(
                    dataset, plan, engine
                )
                note(plan, "device-sort")
                return result
            except spill_mod.SpillOverflow:
                # a sharded hash bucket exceeded its static capacity —
                # exactness wins: take the host path instead
                note(plan, "host-arrow-overflow")
                return _arrow_frequencies(dataset, plan)
            except Exception as exc:  # noqa: BLE001 — classified below
                if classify_memory_pressure(exc) is None:
                    raise
                # device sort buffers did not fit: the last rung of the
                # downgrade chain is Arrow's host group_by
                record_spill_downgrade(
                    "deferred", plan.columns, "host-arrow"
                )
                note(plan, "host-arrow-oom")
                return _arrow_frequencies(dataset, plan)

        return run

    def make_arrow(plan):
        def run():
            note(plan, "host-arrow")
            return _arrow_frequencies(dataset, plan)

        return run

    def make_collector(plan, build_spec, deferred_thunk):
        """Route a spill plan onto the shared fused scan: build its
        CollectorSpec and wire the three exits — success telemetry,
        SpillOverflow -> host Arrow, shared-scan failure -> the plan's
        own deferred re-scan thunk. A spec BUILD failure (geometry or
        key-builder trace issues) quietly keeps the deferred twin."""
        try:
            spec = build_spec()
        except Exception:  # noqa: BLE001
            deferred[plan] = deferred_thunk
            return

        spec.on_success = lambda: note(plan, spec.path)

        def overflow_fallback():
            note(plan, "host-arrow-overflow")
            return _arrow_frequencies(dataset, plan)

        spec.overflow_fallback = overflow_fallback
        spec.scan_fallback = deferred_thunk
        collectors.append(spec)

    for plan in plans:
        # a plan eligible for the device sort path never probes the
        # dictionary at all — no host-side distinct set is built for a
        # high-cardinality numeric key column
        if spill_mod.device_spill_eligible(dataset, plan, engine):
            if use_collectors:
                make_collector(
                    plan,
                    lambda p=plan: spill_mod.single_collector_spec(
                        dataset, p, engine
                    ),
                    make_spill(plan),
                )
            else:
                deferred[plan] = make_spill(plan)
            continue
        # capped distinct counts first: a spilling plan must never
        # materialize an unbounded value set on the host (probe with the
        # REMAINING budget — a plan that cannot fit anyway must not
        # stream up to the full cap into a host dict first)
        sizes_maybe = [
            dataset.dictionary_size_within(c, remaining)
            for c in plan.columns
        ]
        joint = 1
        for s in sizes_maybe:
            if s is None:
                joint = None
                break
            joint *= s + 1  # +1: the null slot
        # debit what _make_dense_ops ACTUALLY allocates (the pow2-padded
        # vector), or plans sized right at the budget would exceed it
        padded = _padded_dense_len(joint) if joint is not None else None
        if padded is not None and padded <= remaining:
            dictionaries = [dataset.dictionary(c) for c in plan.columns]
            sizes = [len(d) + 1 for d in dictionaries]
            requests, ops = _make_dense_ops(
                dataset, plan, sizes, count_dtype
            )
            dense.append((plan, dictionaries, sizes, requests, ops))
            remaining -= padded
        elif (
            len(plan.columns) > 1
            # size-independent gates FIRST: the full-cardinality
            # re-probe below may stream a whole distinct set into host
            # memory, which must never happen for a config-rejected plan
            and spill_mod.joint_spill_config_ok(dataset, plan, engine)
            and (
                full_sizes := [
                    # re-probe the FULL cardinality (bounded by the row
                    # count, which joint_spill_config_ok just capped
                    # below 2^31): a pair of ~10M-cardinality columns
                    # blows straight past the dense probe's budget, but
                    # its joint space fits the sort lanes fine — without
                    # this re-probe such plans fell to host Arrow
                    # (VERDICT r3 next #7)
                    s
                    if s is not None
                    else dataset.dictionary_size_within(
                        c, dataset.num_rows
                    )
                    for c, s in zip(plan.columns, sizes_maybe)
                ]
            )
            and spill_mod.joint_spill_eligible(
                dataset, plan, [s + 1 for s in full_sizes], engine
            )
        ):
            # known per-column cardinalities whose JOINT space exceeds
            # the dense budget but fits the u64 sort lane(s): pack the
            # joint code and take the device sort path
            dictionaries = [dataset.dictionary(c) for c in plan.columns]
            sizes = [len(d) + 1 for d in dictionaries]

            def make_joint(plan, dictionaries, sizes):
                def run():
                    probe = oom_probe_of(dataset)
                    try:
                        if probe is not None:
                            probe("deferred")
                        result = spill_mod.device_spill_joint_frequencies(
                            dataset, plan, engine, dictionaries, sizes
                        )
                    except spill_mod.SpillOverflow:
                        # a sharded hash bucket exceeded its static
                        # capacity: exactness wins, host path instead
                        note(plan, "host-arrow-overflow")
                        return _arrow_frequencies(dataset, plan)
                    except Exception as exc:  # noqa: BLE001
                        if classify_memory_pressure(exc) is None:
                            raise
                        record_spill_downgrade(
                            "deferred", plan.columns, "host-arrow"
                        )
                        note(plan, "host-arrow-oom")
                        return _arrow_frequencies(dataset, plan)
                    note(plan, "device-sort-joint")  # after success
                    return result

                return run

            if use_collectors:
                make_collector(
                    plan,
                    lambda p=plan, d=dictionaries, s=sizes: (
                        spill_mod.joint_collector_spec(
                            dataset, p, engine, d, s
                        )
                    ),
                    make_joint(plan, dictionaries, sizes),
                )
            else:
                deferred[plan] = make_joint(plan, dictionaries, sizes)
        else:
            deferred[plan] = make_arrow(plan)
    return dense, collectors, deferred


def finalize_dense_states(
    dense_specs, states
) -> Dict[FrequencyPlan, FrequenciesAndNumRows]:
    """Decode the shared scan's final (counts, num_rows) states back
    into FrequenciesAndNumRows, one per dense plan."""
    out: Dict[FrequencyPlan, FrequenciesAndNumRows] = {}
    for (plan, dictionaries, sizes, _requests, _ops), state in zip(
        dense_specs, states
    ):
        counts, num_rows = state
        joint = 1
        for s in sizes:
            joint *= s
        out[plan] = _decode_dense(
            plan,
            dictionaries,
            sizes,
            np.asarray(counts)[:joint],  # drop pow2 padding + overflow
            int(num_rows),
        )
    return out


def finalize_collector_states(
    collectors, states, isolate: bool = False, cancel=None, oom_probe=None
) -> Dict[FrequencyPlan, FrequenciesAndNumRows]:
    """Finish every one-pass spill plan from its shared-scan collector
    state. Dispatch order matters for latency: EVERY plan's sort +
    segment-count launches (async) before ANY result is fetched, so
    the per-plan device sorts overlap; then ONE packed transfer brings
    back all the pending scalars and each plan's state object builds
    host-side. ``SpillOverflow`` (sharded hash bucket past capacity)
    takes the plan's host-Arrow fallback. With ``isolate`` set, other
    exceptions become the plan's dict value (the runner's per-plan
    failure-metric contract) instead of propagating. A cancelled
    ``cancel`` token (engine/deadline.py) stops launching new per-plan
    sorts and skips the fetch — under ``isolate`` each unfinished plan
    reports the cancellation as its own failure, otherwise
    ``RunCancelled`` propagates. A finalize whose sort buffers OOM
    (``MemoryPressureError`` via engine/memory.py — ``oom_probe`` is
    the fault-injection hook) downgrades to the plan's deferred re-scan
    path, which itself downgrades to host Arrow under pressure — the
    collector -> deferred -> Arrow chain, each rung recorded."""
    from deequ_tpu.analyzers.spill import SpillOverflow
    from deequ_tpu.engine.deadline import RunCancelled
    from deequ_tpu.engine.pack import packed_device_get

    def _cancelled_exc():
        reason = getattr(cancel, "reason", None) or "cancelled"
        return RunCancelled(f"spill finalize cancelled: {reason}")

    out: Dict[FrequencyPlan, FrequenciesAndNumRows] = {}
    launched = []  # (spec, build) with a slot in the pending tree
    pendings = []
    for spec, state in zip(collectors, states):
        if cancel is not None and cancel.cancelled:
            if not isolate:
                raise _cancelled_exc()
            out[spec.plan] = MetricCalculationException(
                "spill finalize skipped: run cancelled "
                f"({getattr(cancel, 'reason', None) or 'cancelled'})"
            )
            continue
        try:
            if oom_probe is not None:
                oom_probe("finalize")
            pending, build = spec.dispatch(state)
        except Exception as exc:  # noqa: BLE001 — finalize trace died;
            # the data was consumed, so re-scan via the deferred twin
            # (a classified OOM records the downgrade first: the
            # collector -> deferred rung of the chain)
            if classify_memory_pressure(exc) is not None:
                record_spill_downgrade(
                    "finalize", spec.plan.columns, "deferred"
                )
            try:
                out[spec.plan] = spec.scan_fallback()
            except Exception as fallback_exc:  # noqa: BLE001
                if not isolate:
                    raise
                out[spec.plan] = fallback_exc
            continue
        launched.append((spec, build))
        pendings.append(pending)
    if cancel is not None and cancel.cancelled and launched:
        # cancelled between dispatch and fetch: don't pay the blocking
        # device round trip for results nobody will look at
        if not isolate:
            raise _cancelled_exc()
        for spec, _build in launched:
            out[spec.plan] = MetricCalculationException(
                "spill finalize skipped: run cancelled "
                f"({getattr(cancel, 'reason', None) or 'cancelled'})"
            )
        return out
    fetched = packed_device_get(tuple(pendings))
    for (spec, build), got in zip(launched, fetched):
        try:
            out[spec.plan] = build(got)
            spec.on_success()
        except SpillOverflow:
            try:
                out[spec.plan] = spec.overflow_fallback()
            except Exception as exc:  # noqa: BLE001
                if not isolate:
                    raise
                out[spec.plan] = exc
        except Exception as exc:  # noqa: BLE001
            if classify_memory_pressure(exc) is not None:
                # host-side result construction hit pressure: re-scan
                # via the deferred twin (which can itself downgrade)
                record_spill_downgrade(
                    "finalize", spec.plan.columns, "deferred"
                )
                try:
                    out[spec.plan] = spec.scan_fallback()
                    continue
                except Exception as fallback_exc:  # noqa: BLE001
                    exc = fallback_exc
            if not isolate:
                raise
            out[spec.plan] = exc
    return out


def compute_many_frequencies(
    dataset: Dataset,
    plans: Sequence[FrequencyPlan],
    engine: Optional[AnalysisEngine] = None,
    events: Optional[List[dict]] = None,
) -> Dict[FrequencyPlan, FrequenciesAndNumRows]:
    """ALL dense frequency plans ride ONE fused scan (each plan is just a
    scatter-add over different codes, so K plans still cost one data
    pass — the profiler's pass-3 histogram explosion collapses into a
    single job, SURVEY.md §7 hard part #6). Plans whose joint key space
    exceeds the dense cap SPILL: a single numeric column runs the
    device sort + segment-count path (analyzers/spill.py); everything
    else falls back to Arrow's multithreaded host group_by. (The
    AnalysisRunner fuses dense plans into its MAIN scan instead via
    plan_frequency_passes; this entry point runs them standalone.)"""
    engine = engine or AnalysisEngine()
    dense, collectors, deferred = plan_frequency_passes(
        dataset, plans, engine, events
    )
    results: Dict[FrequencyPlan, FrequenciesAndNumRows] = {
        plan: run() for plan, run in deferred.items()
    }
    if dense or collectors:
        states = engine.run_scan(
            dataset,
            [
                (FrequencyScanAdapter(requests), ops)
                for (_p, _d, _s, requests, ops) in dense
            ]
            + [
                (FrequencyScanAdapter(spec.requests), spec.ops)
                for spec in collectors
            ],
        )
        if events is not None and engine.phase_times is not None:
            # same one-event-per-run_scan contract as the runner's
            # fused pass, so _phases-style consumers see every scan
            events.append({"event": "scan_phases", **engine.phase_times})
        results.update(finalize_dense_states(dense, states[: len(dense)]))
        results.update(
            finalize_collector_states(
                collectors,
                states[len(dense):],
                cancel=getattr(engine, "cancel", None),
                oom_probe=oom_probe_of(dataset),
            )
        )
    return results


def _where_mask_full(dataset: Dataset, where: Optional[str]) -> Optional[np.ndarray]:
    """Evaluate a where-filter over the whole table (used by the host
    fallback); returns bool ndarray or None."""
    if where is None:
        return None
    pred = compile_predicate(where, dataset)
    batch = {r.key: dataset.materialize(r) for r in pred.requests}
    batch[ROW_MASK] = np.ones(dataset.num_rows, dtype=bool)
    return np.asarray(jax.device_get(pred.complies(batch))).astype(bool)


def _make_dense_ops(
    dataset: Dataset,
    plan: FrequencyPlan,
    sizes: List[int],
    count_dtype=np.int64,
):
    """(requests, ScanOps) for one dense frequency plan; the ops' state
    is (dense count vector, kept-row count). The count vector dtype is
    i32 when every count provably fits (see _dense_joint_cap)."""
    from deequ_tpu.analyzers.base import ScanOps

    columns = list(plan.columns)
    where_fn = None
    requests = [ColumnRequest(c, "codes") for c in columns] + [
        ColumnRequest(c, "mask") for c in columns
    ]
    if plan.where is not None:
        pred = compile_predicate(plan.where, dataset)
        where_fn = pred.complies
        requests += list(pred.requests)

    joint = 1
    for s in sizes:
        joint *= s
    jnp_count = jnp.int32 if count_dtype == np.int32 else jnp.int64
    # joint codes need int64 lanes once the key space passes 2^31
    code_dtype = jnp.int64 if joint >= 2**31 else jnp.int32
    # count vector padded to pow2 (always > joint, so the overflow slot
    # fits): the compiled scan is then shared across datasets whose key
    # spaces round to the same size, and the per-column SIZES enter as
    # runtime consts rather than baked-in scalars — see ScanOps.consts
    padded_len = _padded_dense_len(joint)

    def init():
        return (
            np.zeros(padded_len, dtype=count_dtype),
            np.int64(0),
        )

    def update(state, batch, consts):
        sizes_arr = consts["sizes"]
        counts, num_rows = state
        rows = batch[ROW_MASK]
        if where_fn is not None:
            rows = rows & where_fn(batch)
        if plan.include_nulls:
            keep = rows
        else:
            any_non_null = jnp.zeros_like(rows)
            for c in columns:
                any_non_null = any_non_null | batch[f"{c}::mask"]
            keep = rows & any_non_null
        code = jnp.zeros(
            batch[f"{columns[0]}::codes"].shape, dtype=code_dtype
        )
        for j, c in enumerate(columns):
            shifted = (batch[f"{c}::codes"] + 1).astype(code_dtype)
            code = code * sizes_arr[j] + shifted  # null (-1) -> slot 0
        # masked scatter-add; rejected rows go to the overflow slot.
        # The scatter MUST run in i32: under x64, jnp.bincount scatters
        # in int64, which TPUs emulate at ~30x the i32 scatter cost
        # (measured 148ms vs 5ms per 2M-row batch). Batches are far
        # below 2^31 rows, so i32 per-batch counts are exact; the
        # cross-batch carry add widens to the state dtype.
        code = jnp.where(keep, code, padded_len - 1)
        per_batch = jnp.zeros(padded_len, dtype=jnp.int32).at[
            jnp.clip(code, 0, padded_len - 1)
        ].add(1)
        counts = counts + per_batch.astype(jnp_count)
        return counts, num_rows + jnp.sum(keep, dtype=jnp.int64)

    token = None
    if plan.where is None or compile_predicate(
        plan.where, dataset
    ).dataset_independent:
        # closure content beyond consts: columns, padded_len, dtypes,
        # null policy, the where expression
        token = (
            "dense-frequencies",
            plan.columns,
            plan.include_nulls,
            plan.where,
            padded_len,
            str(np.dtype(code_dtype)),
            str(np.dtype(count_dtype)),
        )
    ops = ScanOps(
        init,
        update,
        lambda a, b: (a[0] + b[0], a[1] + b[1]),
        consts={"sizes": np.asarray(sizes, dtype=code_dtype)},
        cache_token=token,
    )
    return requests, ops


def _decode_joint_codes(
    n_columns: int,
    observed: np.ndarray,
    dictionaries: List[np.ndarray],
    sizes: List[int],
) -> np.ndarray:
    key_arr = np.empty((len(observed), n_columns), dtype=object)
    remaining = observed.copy()
    for j in range(n_columns - 1, -1, -1):
        slot = remaining % sizes[j]
        remaining = remaining // sizes[j]
        dictionary = dictionaries[j]
        decoded = np.empty(len(slot), dtype=object)
        non_null = slot > 0
        if non_null.any():
            decoded[non_null] = dictionary[slot[non_null] - 1]
        decoded[~non_null] = None
        key_arr[:, j] = decoded
    return key_arr


def _decode_dense(
    plan: FrequencyPlan,
    dictionaries: List[np.ndarray],
    sizes: List[int],
    counts: np.ndarray,
    num_rows: int,
) -> FrequenciesAndNumRows:
    columns = list(plan.columns)
    observed = np.nonzero(counts)[0]
    return FrequenciesAndNumRows(
        tuple(columns),
        None,
        counts[observed],
        num_rows,
        lazy_codes=(observed, list(dictionaries), list(sizes)),
    )


class FrequencyScanAdapter:
    """Adapter so frequency passes ride the shared scan engine (and the
    explicit shard_map step — see __graft_entry__): a fixed request
    list standing in for an analyzer's device_requests."""

    def __init__(self, requests):
        self._requests = requests

    def device_requests(self, ds):
        return self._requests


def _free_column_name(columns: List[str], base: str = "__count__") -> str:
    name = base
    while name in columns:
        name += "_"
    return name


def _grouped_to_frequencies(
    grouped: pa.Table,
    columns: List[str],
    count_col: str,
    num_rows: int,
) -> FrequenciesAndNumRows:
    """Arrow group_by output -> FrequenciesAndNumRows (the one decode)."""
    counts = grouped.column(count_col).to_numpy(zero_copy_only=False)
    key_arr = np.empty((len(counts), len(columns)), dtype=object)
    for j, c in enumerate(columns):
        key_arr[:, j] = np.asarray(grouped.column(c).to_pylist(), dtype=object)
    return FrequenciesAndNumRows(
        tuple(columns), key_arr, counts.astype(np.int64), num_rows
    )


def _normalize_float_keys(table: pa.Table, columns: List[str]) -> pa.Table:
    """Spark grouping-key normalization for float key columns (-0.0 ->
    0.0, all NaN payloads -> one canonical NaN): the ONE shared rule,
    data.table.normalize_float_grouping_keys. tests/goldens neg_zero."""
    from deequ_tpu.data.table import normalize_float_grouping_keys

    for c in columns:
        col = table.column(c)
        normalized = normalize_float_grouping_keys(col)
        if normalized is not col:
            table = table.set_column(
                table.schema.get_field_index(c), c, normalized
            )
    return table


def _frequencies_of_table(
    columns: List[str], table: pa.Table
) -> FrequenciesAndNumRows:
    table = _normalize_float_keys(table, columns)
    grouped = table.group_by(columns).aggregate([([], "count_all")])
    return _grouped_to_frequencies(
        grouped, columns, "count_all", int(table.num_rows)
    )


def _arrow_frequencies(
    dataset: Dataset, plan: FrequencyPlan
) -> FrequenciesAndNumRows:
    """Host fallback for huge joint key spaces: Arrow's multithreaded
    C++ group_by (the 'spill' strategy of SURVEY.md §7 hard part #1).
    Without a where-filter this STREAMS record batches — group_by per
    chunk, then the vectorized sparse merge — so memory is O(chunk +
    distinct), and parquet sources are never fully loaded."""
    from deequ_tpu.analyzers.spill import _count_data_pass

    _count_data_pass()  # host group_by reads the whole source once
    columns = list(plan.columns)
    if plan.where is None:
        # group each chunk in Arrow, stash the (small) grouped tables,
        # and run ONE final group_by over their concatenation — keys
        # never round-trip through Python objects, and the cost is
        # O(rows + total_partial_groups), not O(chunks x distinct)
        parts: List[pa.Table] = []
        num_rows = 0
        for record_batch in dataset.record_batches(columns):
            table = _normalize_float_keys(
                pa.Table.from_batches([record_batch]), columns
            )
            if not plan.include_nulls:
                non_null = np.zeros(table.num_rows, dtype=bool)
                for c in columns:
                    col = table.column(c)
                    non_null |= ~np.asarray(
                        col.is_null().combine_chunks()
                    )
                table = table.filter(pa.array(non_null))
            num_rows += table.num_rows
            parts.append(
                table.group_by(columns).aggregate([([], "count_all")])
            )
        if not parts:
            return FrequenciesAndNumRows(
                tuple(columns),
                np.empty((0, len(columns)), dtype=object),
                np.zeros(0, dtype=np.int64),
                0,
            )
        combined = pa.concat_tables(parts)
        grouped = combined.group_by(columns).aggregate(
            [("count_all", "sum")]
        )
        return _grouped_to_frequencies(
            grouped, columns, "count_all_sum", num_rows
        )
    # where-filter: the predicate needs full device reprs — materialize
    table = dataset.table.select(columns)
    mask = _where_mask_full(dataset, plan.where)
    if not plan.include_nulls:
        non_null = np.zeros(dataset.num_rows, dtype=bool)
        for c in columns:
            non_null |= dataset.materialize(ColumnRequest(c, "mask"))
        mask = non_null if mask is None else (mask & non_null)
    if mask is not None:
        table = table.filter(pa.array(mask))
    return _frequencies_of_table(columns, table)


def plans_for(
    analyzers: Sequence[GroupingAnalyzer],
) -> Dict[FrequencyPlan, List[GroupingAnalyzer]]:
    """Group analyzers by their shared frequency plan (SURVEY.md §2.4
    step 5: ONE pass per (grouping columns, filter))."""
    by_plan: Dict[FrequencyPlan, List[GroupingAnalyzer]] = {}
    for analyzer in analyzers:
        plan = FrequencyPlan(
            tuple(analyzer.grouping_columns()),
            analyzer.filter_condition,
            getattr(analyzer, "include_nulls", False),
        )
        by_plan.setdefault(plan, []).append(analyzer)
    return by_plan


def finalize_grouping_metrics(
    by_plan: Dict[FrequencyPlan, List[GroupingAnalyzer]],
    frequencies: Dict[FrequencyPlan, object],
    aggregate_with,
    save_states_with,
) -> Dict[Analyzer, Metric]:
    """Per-analyzer metric finalization over computed frequency states;
    a plan may map to an EXCEPTION, which degrades to failure metrics
    for exactly that plan's analyzers."""
    metrics: Dict[Analyzer, Metric] = {}
    for plan, group in by_plan.items():
        result = frequencies.get(plan)
        for analyzer in group:
            try:
                if isinstance(result, BaseException):
                    raise result
                state = result
                if aggregate_with is not None:
                    prior = aggregate_with.load(analyzer)
                    if prior is not None:
                        state = FrequenciesAndNumRows.merge(state, prior)
                if save_states_with is not None:
                    save_states_with.persist(analyzer, state)
                metrics[analyzer] = analyzer.compute_metric_from_state(state)
            except Exception as exc:  # noqa: BLE001
                metrics[analyzer] = analyzer.to_failure_metric(exc)
    return metrics


def run_grouping_analyzers(
    dataset: Dataset,
    analyzers: Sequence[GroupingAnalyzer],
    engine: Optional[AnalysisEngine],
    aggregate_with,
    save_states_with,
    metadata=None,
) -> Dict[Analyzer, Metric]:
    """Standalone grouping execution (the AnalysisRunner fuses dense
    plans into its main scan instead; this path serves direct callers)."""
    by_plan = plans_for(analyzers)
    try:
        all_frequencies = compute_many_frequencies(
            dataset,
            list(by_plan.keys()),
            engine,
            events=None if metadata is None else metadata.events,
        )
    except Exception as exc:  # noqa: BLE001
        return {
            analyzer: analyzer.to_failure_metric(exc)
            for group in by_plan.values()
            for analyzer in group
        }
    return finalize_grouping_metrics(
        by_plan, all_frequencies, aggregate_with, save_states_with
    )


# --------------------------------------------------------------------------
# Concrete grouping analyzers
# --------------------------------------------------------------------------


def _normalize_columns(columns: Union[str, Sequence[str]]) -> Tuple[str, ...]:
    if isinstance(columns, str):
        return (columns,)
    return tuple(columns)


@dataclass(frozen=True)
class _FrequencyAnalyzer(GroupingAnalyzer):
    columns: Tuple[str, ...] = ()
    where: Optional[str] = None

    def __init__(
        self, columns: Union[str, Sequence[str]], where: Optional[str] = None
    ):
        object.__setattr__(self, "columns", _normalize_columns(columns))
        object.__setattr__(self, "where", where)

    def grouping_columns(self) -> List[str]:
        return list(self.columns)

    @property
    def filter_condition(self) -> Optional[str]:
        return self.where

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN if len(self.columns) == 1 else Entity.MULTICOLUMN

    @property
    def instance(self) -> str:
        return ",".join(self.columns)

    def compute_metric_from_state(self, state) -> Metric:
        if state is None or state.num_rows == 0:
            return self.to_failure_metric(
                EmptyStateException(
                    f"Empty state for analyzer {self.name}."
                )
            )
        return DoubleMetric.success(
            self.entity, self.name, self.instance, self._value(state)
        )

    def _value(self, state: FrequenciesAndNumRows) -> float:
        raise NotImplementedError


class CountDistinct(_FrequencyAnalyzer):
    """Exact distinct count (reference: analyzers/CountDistinct.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return float(state.num_groups)


class Distinctness(_FrequencyAnalyzer):
    """#distinct / #rows (reference: analyzers/Distinctness.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return state.num_groups / state.num_rows


class Uniqueness(_FrequencyAnalyzer):
    """Fraction of values occurring exactly once (reference:
    analyzers/Uniqueness.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return float(state.count_unique_groups()) / state.num_rows


class UniqueValueRatio(_FrequencyAnalyzer):
    """#unique / #distinct (reference: analyzers/UniqueValueRatio.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return float(state.count_unique_groups()) / state.num_groups


class Entropy(_FrequencyAnalyzer):
    """Shannon entropy of the value distribution (reference:
    analyzers/Entropy.scala); computed over non-null groups."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return state.entropy_nats()


class MutualInformation(_FrequencyAnalyzer):
    """Mutual information of two columns (reference:
    analyzers/MutualInformation.scala) — derived from the joint frequency
    table; rows with any null in the pair are excluded."""

    def preconditions(self) -> List[Precondition]:
        from deequ_tpu.analyzers.base import exactly_n_columns

        return [exactly_n_columns(self.columns, 2)] + super().preconditions()

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def _value(self, state: FrequenciesAndNumRows) -> float:
        keep = state.non_null_group_mask()
        keys = state.keys[keep]
        counts = state.counts[keep].astype(np.float64)
        total = counts.sum()
        if total == 0:
            raise EmptyStateException("MutualInformation over empty state.")
        p_joint = counts / total
        left: Dict[object, float] = {}
        right: Dict[object, float] = {}
        for row, p in zip(keys, p_joint):
            left[row[0]] = left.get(row[0], 0.0) + p
            right[row[1]] = right.get(row[1], 0.0) + p
        mi = 0.0
        for row, p in zip(keys, p_joint):
            mi += p * math.log(p / (left[row[0]] * right[row[1]]))
        return float(mi)


@dataclass(frozen=True)
class Histogram(GroupingAnalyzer):
    """Full value distribution, null values kept as a ``NullValue`` bin,
    detail capped at ``max_detail_bins`` (reference:
    analyzers/Histogram.scala — runs its own groupBy; SURVEY.md §2.2)."""

    column: str = ""
    max_detail_bins: int = 1000
    where: Optional[str] = None

    def __init__(
        self,
        column: str,
        max_detail_bins: int = 1000,
        where: Optional[str] = None,
    ):
        object.__setattr__(self, "column", column)
        object.__setattr__(self, "max_detail_bins", max_detail_bins)
        object.__setattr__(self, "where", where)

    include_nulls = True

    def grouping_columns(self) -> List[str]:
        return [self.column]

    @property
    def filter_condition(self) -> Optional[str]:
        return self.where

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column)]

    def compute_metric_from_state(self, state) -> Metric:
        if state is None:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Histogram.")
            )
        top_keys, top_counts = state.top_groups(self.max_detail_bins)
        counts: Dict[str, int] = {}
        for value, count in zip(top_keys, top_counts):
            label = NULL_VALUE if value is None else str(value)
            counts[label] = int(count)
        metric = HistogramMetric.from_counts(
            "Histogram", self.instance, counts, state.num_rows
        )
        # number_of_bins reflects the FULL distinct count even when the
        # detail is capped (reference behavior)
        from deequ_tpu.metrics.distribution import Distribution

        full = Distribution(metric.value.get().values, state.num_groups)
        return HistogramMetric(
            Entity.COLUMN, "Histogram", self.instance, type(metric.value)(full)
        )
