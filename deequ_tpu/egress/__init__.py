"""Streaming row-level egress: on-scan bad-row extraction to a
partitioned clean/quarantine parquet split. See docs/EGRESS.md.

- :class:`RowLevelSink` — the user-facing request (pass to
  ``VerificationRunBuilder.with_row_level_sink`` or ``row_level_sink=``
  on ``do_verification_run`` / ``service.RunRequest``);
- :class:`EgressReport` — what one run's egress produced
  (``sink.report`` / ``result.row_level_egress``);
- :data:`BATCH_QUARANTINED` — the ``__failed_constraints__`` marker for
  rows whose whole batch was quarantined by the resilience layer;
- ``plan_row_sink`` / ``finalize_row_sink`` — the run integration
  surface (used by ``verification/suite.py``).
"""

from deequ_tpu.egress.plan import (
    RowSinkPlan,
    finalize_row_sink,
    plan_row_sink,
)
from deequ_tpu.egress.writer import (
    BATCH_QUARANTINED,
    EgressReport,
    QuarantineWriter,
    RowLevelSink,
)

__all__ = [
    "BATCH_QUARANTINED",
    "EgressReport",
    "QuarantineWriter",
    "RowLevelSink",
    "RowSinkPlan",
    "finalize_row_sink",
    "plan_row_sink",
]
