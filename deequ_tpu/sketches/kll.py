"""KLL quantile sketch with a TPU-friendly split of labor.

Reference: the reference implements the KLL compactor hierarchy as
``QuantileNonSample.scala`` + ``KLLSketchSerializer`` (SURVEY.md §2.3):
fixed-capacity compactors; merge = concatenate + recompress. Its per-row
update is a Tungsten aggregate. A literal port would be scalar,
data-dependent control flow — hostile to XLA (SURVEY.md §7 hard part #2).

TPU design: a sorted batch of B items, strided by 2^l with a random
offset, IS l rounds of KLL compaction applied at once. So the device
kernel (inside the shared fused scan) sorts the batch and emits k
strided samples at static level l = ceil(log2(B / k)) — fixed shapes,
jit-friendly, and only k floats cross the device->host boundary per
batch. The host keeps the compactor hierarchy (tiny arrays) and merges
batch contributions by concatenate + recompress, which is also the
cross-dataset/incremental merge.

Rank-error behavior matches the KLL family: O(1/k) with capacity
shrinking by ``shrinking_factor`` per level down from the top.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

DEFAULT_SKETCH_SIZE = 2048
DEFAULT_SHRINKING_FACTOR = 0.64
MIN_CAPACITY = 8


@dataclass(frozen=True)
class KLLParameters:
    """Reference: KLLParameters(sketchSize, shrinkingFactor, maxDetailBins)."""

    sketch_size: int = DEFAULT_SKETCH_SIZE
    shrinking_factor: float = DEFAULT_SHRINKING_FACTOR
    number_of_buckets: int = 100


class KLLSketchState:
    """Host-side compactor hierarchy. ``levels[i]`` holds unweighted items
    of weight 2^i. Mergeable (concat + recompress) => a monoid, so it
    rides run_on_aggregated_states like every other state."""

    def __init__(
        self,
        params: KLLParameters = KLLParameters(),
        levels: Optional[List[np.ndarray]] = None,
        count: int = 0,
        min_value: float = math.inf,
        max_value: float = -math.inf,
        seed: int = 0x5EED,
    ):
        self.params = params
        self.levels: List[np.ndarray] = (
            [np.asarray(lv, dtype=np.float64) for lv in levels]
            if levels
            else [np.empty(0, dtype=np.float64)]
        )
        self.count = int(count)
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self._rng = np.random.default_rng(seed)

    # -- capacities -----------------------------------------------------

    def _capacity(self, level: int) -> int:
        """Top level has capacity k; lower levels shrink geometrically."""
        height = len(self.levels)
        depth = height - 1 - level
        cap = int(
            math.ceil(
                self.params.sketch_size
                * (self.params.shrinking_factor ** depth)
            )
        )
        return max(MIN_CAPACITY, cap)

    # -- update ---------------------------------------------------------

    def update_batch(self, values: np.ndarray) -> None:
        values = np.asarray(values, dtype=np.float64)
        values = values[np.isfinite(values)]
        if values.size == 0:
            return
        self.count += int(values.size)
        self.min_value = min(self.min_value, float(values.min()))
        self.max_value = max(self.max_value, float(values.max()))
        self.levels[0] = np.concatenate([self.levels[0], values])
        self._compress()

    def add_pre_compacted(
        self,
        values: np.ndarray,
        level: int,
        count: int,
        min_value: float,
        max_value: float,
        assume_finite: bool = False,
    ) -> None:
        """Insert items already compacted to ``level`` (the device batch
        kernel's output); weights 2^level.

        ``assume_finite``: skip the sentinel/NaN safety net. The
        vectorized KLL unit (engine/vectorize.py) masks non-finite
        values into the +inf sort sentinel on device and marks those
        sample slots invalid BEFORE the fetch, so its folded samples
        are finite by construction — at 40 columns per batch the
        redundant isfinite scan + boolean-index copy was measurable
        host epilogue time."""
        values = np.asarray(values, dtype=np.float64)
        if not assume_finite:
            values = values[np.isfinite(values)]  # sentinel/NaN net
        while len(self.levels) <= level:
            self.levels.append(np.empty(0, dtype=np.float64))
        if values.size:
            self.levels[level] = np.concatenate(
                [self.levels[level], values]
            )
        self.count += int(count)
        if count > 0:
            self.min_value = min(self.min_value, float(min_value))
            self.max_value = max(self.max_value, float(max_value))
        self._compress()

    def _compress(self) -> None:
        level = 0
        while level < len(self.levels):
            if self.levels[level].size > self._capacity(level):
                self._compact_level(level)
            level += 1

    def _compact_level(self, level: int) -> None:
        buffer = np.sort(self.levels[level])
        if buffer.size % 2 == 1:
            # keep one random end unpaired at this level
            if self._rng.integers(0, 2):
                leftover, buffer = buffer[-1:], buffer[:-1]
            else:
                leftover, buffer = buffer[:1], buffer[1:]
        else:
            leftover = np.empty(0, dtype=np.float64)
        offset = int(self._rng.integers(0, 2))
        promoted = buffer[offset::2]
        self.levels[level] = np.asarray(leftover, dtype=np.float64)
        if level + 1 >= len(self.levels):
            self.levels.append(np.empty(0, dtype=np.float64))
        self.levels[level + 1] = np.concatenate(
            [self.levels[level + 1], promoted]
        )

    # -- merge (monoid) -------------------------------------------------

    @staticmethod
    def merge(a: "KLLSketchState", b: "KLLSketchState") -> "KLLSketchState":
        if a.params != b.params:
            raise ValueError("cannot merge KLL sketches with different params")
        height = max(len(a.levels), len(b.levels))
        levels = []
        for i in range(height):
            la = a.levels[i] if i < len(a.levels) else np.empty(0)
            lb = b.levels[i] if i < len(b.levels) else np.empty(0)
            levels.append(
                np.concatenate(
                    [np.asarray(la, np.float64), np.asarray(lb, np.float64)]
                )
            )
        out = KLLSketchState(
            a.params,
            levels,
            a.count + b.count,
            min(a.min_value, b.min_value),
            max(a.max_value, b.max_value),
        )
        out._compress()
        return out

    # -- queries --------------------------------------------------------

    def _weighted_items(self) -> Tuple[np.ndarray, np.ndarray]:
        values = []
        weights = []
        for level, buf in enumerate(self.levels):
            if buf.size:
                values.append(buf)
                weights.append(np.full(buf.size, 2.0 ** level))
        if not values:
            return np.empty(0), np.empty(0)
        v = np.concatenate(values)
        w = np.concatenate(weights)
        order = np.argsort(v, kind="stable")
        return v[order], w[order]

    @property
    def is_empty(self) -> bool:
        return self.count == 0

    def quantile(self, q: float) -> float:
        """Smallest sketched value whose cumulative weight >= q * total."""
        return self.quantiles([q])[0]

    def quantiles(self, qs: Sequence[float]) -> List[float]:
        """All requested quantiles from ONE sort + cumsum: the default
        profile asks for 99 percentiles per column, so per-call re-sorts
        of the sketch would dominate host-side finalize time."""
        v, w = self._weighted_items()
        if v.size == 0:
            return [math.nan for _ in qs]
        cum = np.cumsum(w)
        targets = np.asarray(list(qs), dtype=np.float64) * cum[-1]
        idx = np.minimum(
            np.searchsorted(cum, targets, side="left"), v.size - 1
        )
        return [float(x) for x in v[idx]]

    def rank(self, x: float) -> float:
        """Estimated number of items <= x."""
        v, w = self._weighted_items()
        if v.size == 0:
            return 0.0
        idx = np.searchsorted(v, x, side="right")
        return float(np.sum(w[:idx]))

    def cdf(self, x: float) -> float:
        total = self.count
        return self.rank(x) / total if total else math.nan

    def buckets(self, number_of_buckets: int) -> List[Tuple[float, float, int]]:
        """Equi-width bucketing (low, high, count) over [min, max]."""
        if self.is_empty:
            return []
        lo, hi = self.min_value, self.max_value
        if hi == lo:
            return [(lo, hi, self.count)]
        edges = np.linspace(lo, hi, number_of_buckets + 1)
        ranks = [self.rank(edge) for edge in edges]
        ranks[0] = 0.0
        ranks[-1] = float(self.count)
        out = []
        for i in range(number_of_buckets):
            out.append(
                (
                    float(edges[i]),
                    float(edges[i + 1]),
                    int(round(ranks[i + 1] - ranks[i])),
                )
            )
        return out

    # -- serde ----------------------------------------------------------

    def to_arrays(self) -> Dict[str, np.ndarray]:
        flat = np.concatenate(
            [np.asarray(lv, np.float64) for lv in self.levels]
        ) if self.levels else np.empty(0)
        sizes = np.asarray([lv.size for lv in self.levels], dtype=np.int64)
        return {
            "items": flat,
            "level_sizes": sizes,
            "count": np.int64(self.count),
            "min_value": np.float64(self.min_value),
            "max_value": np.float64(self.max_value),
            "params": np.asarray(
                [
                    self.params.sketch_size,
                    self.params.shrinking_factor,
                    self.params.number_of_buckets,
                ],
                dtype=np.float64,
            ),
        }

    @staticmethod
    def from_arrays(data) -> "KLLSketchState":
        params = KLLParameters(
            int(data["params"][0]),
            float(data["params"][1]),
            int(data["params"][2]),
        )
        sizes = data["level_sizes"]
        flat = data["items"]
        levels = []
        pos = 0
        for size in sizes:
            levels.append(np.asarray(flat[pos : pos + int(size)]))
            pos += int(size)
        return KLLSketchState(
            params,
            levels,
            int(data["count"]),
            float(data["min_value"]),
            float(data["max_value"]),
        )
