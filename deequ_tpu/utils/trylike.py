"""Scala-style ``Try`` values: failures are data, not control flow.

The reference wraps every metric value in ``Try[Value]`` so a failed
analyzer (missing column, empty state, cast error) produces a *failure
metric* and the run still completes (reference:
``src/main/scala/com/amazon/deequ/metrics/Metric.scala``; SURVEY.md §2.1,
§5.3). This module is the Python equivalent used throughout deequ_tpu.
"""

from __future__ import annotations

from typing import Callable, Generic, TypeVar

T = TypeVar("T")
U = TypeVar("U")


class Try(Generic[T]):
    """Either a ``Success(value)`` or a ``Failure(exception)``."""

    @property
    def is_success(self) -> bool:
        raise NotImplementedError

    @property
    def is_failure(self) -> bool:
        return not self.is_success

    def get(self) -> T:
        raise NotImplementedError

    def get_or_else(self, default: U) -> T | U:
        return self.get() if self.is_success else default

    @property
    def exception(self) -> BaseException | None:
        return None

    def map(self, fn: Callable[[T], U]) -> "Try[U]":
        raise NotImplementedError

    def recover(self, fn: Callable[[BaseException], U]) -> "Try[T | U]":
        """Scala's ``Try.recover``: a Success passes through; a Failure
        becomes ``Try.of(lambda: fn(exception))`` — so a raising
        recovery function is itself a Failure, never an escape."""
        raise NotImplementedError

    @staticmethod
    def of(fn: Callable[[], T]) -> "Try[T]":
        try:
            return Success(fn())
        except Exception as exc:  # noqa: BLE001 — failures-as-values by design
            return Failure(exc)

    @staticmethod
    def of_retry(fn: Callable[[], T], attempts: int) -> "Try[T]":
        """``Try.of`` with up to ``attempts`` total tries: re-run ``fn``
        on any Exception until one succeeds or the budget is spent, then
        carry the LAST failure. No backoff — callers that need delays
        use the engine's RetryPolicy; this is the value-level analog for
        cheap idempotent thunks (repository reads, metric recompute)."""
        result: Try[T] = Failure(
            ValueError(f"of_retry needs attempts >= 1, got {attempts}")
        )
        for _ in range(max(int(attempts), 0)):
            result = Try.of(fn)
            if result.is_success:
                return result
        return result


class Success(Try[T]):
    __slots__ = ("_value",)

    def __init__(self, value: T):
        self._value = value

    @property
    def is_success(self) -> bool:
        return True

    def get(self) -> T:
        return self._value

    def map(self, fn: Callable[[T], U]) -> Try[U]:
        return Try.of(lambda: fn(self._value))

    def recover(self, fn: Callable[[BaseException], U]) -> Try[T]:
        return self

    def __repr__(self) -> str:
        return f"Success({self._value!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Success) and other._value == self._value

    def __hash__(self) -> int:
        return hash(("Success", self._value))


class Failure(Try[T]):
    __slots__ = ("_exception",)

    def __init__(self, exception: BaseException):
        self._exception = exception

    @property
    def is_success(self) -> bool:
        return False

    def get(self) -> T:
        raise self._exception

    @property
    def exception(self) -> BaseException:
        return self._exception

    def map(self, fn: Callable[[T], U]) -> Try[U]:
        return Failure(self._exception)

    def recover(self, fn: Callable[[BaseException], U]) -> Try[U]:
        return Try.of(lambda: fn(self._exception))

    def __repr__(self) -> str:
        return f"Failure({self._exception!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Failure)
            and type(other._exception) is type(self._exception)
            and str(other._exception) == str(self._exception)
        )

    def __hash__(self) -> int:
        return hash(("Failure", type(self._exception), str(self._exception)))
