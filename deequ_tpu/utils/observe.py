"""Observability: per-pass wall-time metadata + jax.profiler hooks.

The reference has NO in-repo execution tracing — observability is
delegated to the Spark UI (SURVEY.md §5.1 calls this "a gap we can
exceed"). Here every analysis run records a :class:`PassTiming` per
engine pass (fused scan, frequency pass, direct analyzers), attached to
the AnalyzerContext / VerificationResult, and :func:`profiler_trace`
wraps a block in a jax.profiler trace whose dump opens in
TensorBoard/XProf for kernel-level timing.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional


@dataclass
class PassTiming:
    name: str  # "scan" | "grouping" | "direct" | custom
    wall_s: float
    rows: int
    num_analyzers: int

    @property
    def rows_per_sec(self) -> float:
        return self.rows / self.wall_s if self.wall_s > 0 else 0.0


@dataclass
class RunMetadata:
    """Timings for one AnalysisRunner run, plus notable engine events
    (e.g. grouping plans spilling out of the dense device path — a user
    must be able to SEE why a high-card pass got slower)."""

    passes: List[PassTiming] = field(default_factory=list)
    events: List[dict] = field(default_factory=list)

    @property
    def total_wall_s(self) -> float:
        return sum(p.wall_s for p in self.passes)

    def record(
        self, name: str, wall_s: float, rows: int, num_analyzers: int
    ) -> None:
        self.passes.append(PassTiming(name, wall_s, rows, num_analyzers))

    def merge(self, other: Optional["RunMetadata"]) -> "RunMetadata":
        """Always a FRESH instance — never alias a mutable passes list
        between contexts."""
        if other is None:
            return RunMetadata(list(self.passes), list(self.events))
        return RunMetadata(
            self.passes + other.passes, self.events + other.events
        )

    @staticmethod
    def merge_optional(
        a: Optional["RunMetadata"], b: Optional["RunMetadata"]
    ) -> Optional["RunMetadata"]:
        if a is None and b is None:
            return None
        if a is None:
            return b.merge(None)
        return a.merge(b)

    def as_records(self) -> List[dict]:
        return [
            {
                "pass": p.name,
                "wall_s": round(p.wall_s, 6),
                "rows": p.rows,
                "num_analyzers": p.num_analyzers,
                "rows_per_sec": round(p.rows_per_sec, 1),
            }
            for p in self.passes
        ]


@contextlib.contextmanager
def timed_pass(
    metadata: Optional[RunMetadata],
    name: str,
    rows: int,
    num_analyzers: int,
) -> Iterator[None]:
    """Time a pass (and annotate it for an active jax.profiler trace)."""
    if metadata is None:
        yield
        return
    import jax

    start = time.perf_counter()
    with jax.profiler.TraceAnnotation(f"deequ_tpu:{name}"):
        yield
    metadata.record(name, time.perf_counter() - start, rows, num_analyzers)


@contextlib.contextmanager
def profiler_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace of the wrapped block into
    ``log_dir`` (open with TensorBoard's profile plugin / XProf)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
