"""Predicate DSL unit tests, incl. SQL three-valued-logic regressions."""

import pyarrow as pa
import pytest

from deequ_tpu.analyzers import Compliance, Maximum, Mean
from deequ_tpu.data import Dataset
from deequ_tpu.sql import PredicateParseError, parse_predicate


def compliance(ds, predicate):
    metric = Compliance("t", predicate).calculate(ds)
    assert metric.value.is_success, metric.value
    return metric.value.get()


@pytest.fixture
def numeric_ds():
    return Dataset.from_pydict({"x": [0, 1, 2, 3], "y": [3, 2, 1, 0]})


class TestPredicates:
    def test_comparisons(self, numeric_ds):
        assert compliance(numeric_ds, "x >= 2") == 0.5
        assert compliance(numeric_ds, "x < y") == 0.5
        assert compliance(numeric_ds, "x + y = 3") == 1.0
        assert compliance(numeric_ds, "x * 2 > y") == 0.5

    def test_boolean_logic(self, numeric_ds):
        assert compliance(numeric_ds, "x > 0 AND y > 0") == 0.5
        assert compliance(numeric_ds, "x = 0 OR y = 0") == 0.5
        assert compliance(numeric_ds, "NOT (x = 0)") == 0.75

    def test_between(self, numeric_ds):
        assert compliance(numeric_ds, "x BETWEEN 1 AND 2") == 0.5

    def test_in_list_numeric(self, numeric_ds):
        assert compliance(numeric_ds, "x IN (0, 3)") == 0.5
        assert compliance(numeric_ds, "x NOT IN (0, 3)") == 0.5

    def test_in_list_with_null_literal(self, numeric_ds):
        # SQL 3VL: x IN (1, NULL) is TRUE only on a match, else NULL
        assert compliance(numeric_ds, "x IN (1, NULL)") == 0.25
        assert compliance(numeric_ds, "x IN (NULL)") == 0.0
        # x NOT IN (1, NULL): never TRUE (non-matches are NULL)
        assert compliance(numeric_ds, "x NOT IN (1, NULL)") == 0.0

    def test_null_comparisons_not_compliant(self):
        ds = Dataset.from_arrow(
            pa.table({"x": pa.array([1.0, None, 3.0], pa.float64())})
        )
        assert compliance(ds, "x > 0") == pytest.approx(2 / 3)
        assert compliance(ds, "x IS NULL") == pytest.approx(1 / 3)
        assert compliance(ds, "x IS NOT NULL") == pytest.approx(2 / 3)

    def test_division_by_zero_is_null(self, numeric_ds):
        # y = 0 in the last row -> x / y is NULL there
        assert compliance(numeric_ds, "x / y >= 0") == 0.75

    def test_string_like(self):
        ds = Dataset.from_pydict({"s": ["apple", "banana", "cherry", None]})
        assert compliance(ds, "s LIKE 'b%'") == 0.25
        assert compliance(ds, "s RLIKE 'an'") == 0.25
        assert compliance(ds, "s NOT LIKE 'b%'") == 0.5  # null not compliant

    def test_length_function(self):
        ds = Dataset.from_pydict({"s": ["a", "bb", "ccc", None]})
        assert compliance(ds, "LENGTH(s) >= 2") == 0.5

    def test_parse_errors(self):
        with pytest.raises(PredicateParseError):
            parse_predicate("x >>> 1")
        with pytest.raises(PredicateParseError):
            parse_predicate("AND x")

    def test_string_column_to_column_comparison(self):
        """Two string columns compare by VALUE, not by dictionary code
        (codes come from unrelated dictionaries in order of appearance)."""
        ds = Dataset.from_pydict(
            {"a": ["x", "y", "z", "w"], "b": ["x", "q", "z", "x"]}
        )
        assert compliance(ds, "a = b") == 0.5
        assert compliance(ds, "a != b") == 0.5
        # lexicographic: x<x F, y<q F, z<z F, w<x T
        assert compliance(ds, "a < b") == 0.25
        assert compliance(ds, "a >= b") == 0.75

    def test_string_column_literal_ordering(self):
        ds = Dataset.from_pydict({"s": ["apple", "pear", "zebra", None]})
        assert compliance(ds, "s >= 'pear'") == 0.5
        assert compliance(ds, "'pear' <= s") == 0.5
        assert compliance(ds, "s < 'b'") == 0.25

    def test_string_numeric_mix_rejected(self):
        """Comparing a string column to a numeric operand (or doing
        arithmetic on codes) degrades to a failure METRIC — never a
        silent wrong answer, never a raised exception."""
        ds = Dataset.from_pydict({"s": ["a", "b"], "x": [1.0, 2.0]})
        for pred in ("s = 1", "s < x", "s + 1 > 0"):
            metric = Compliance("t", pred).calculate(ds)
            assert metric.value.is_failure, pred


class TestNullableBoolean:
    def test_numeric_analyzers_on_nullable_bool(self):
        ds = Dataset.from_arrow(
            pa.table({"b": pa.array([True, None, False, True])})
        )
        mean = Mean("b").calculate(ds)
        assert mean.value.is_success, mean.value
        assert mean.value.get() == pytest.approx(2 / 3)
        assert Maximum("b").calculate(ds).value.get() == 1.0


class TestR4GrammarExtensions:
    """CASE WHEN / COALESCE / string functions / date literals
    (VERDICT r3 next #6 — toward the reference's Spark SQL surface)."""

    @pytest.fixture
    def strings_ds(self):
        return Dataset.from_pydict(
            {
                "s": ["  Apple ", "banana", "CHERRY", None, "apple"],
                "x": [1.0, None, 3.0, 4.0, None],
                "y": [10.0, 20.0, None, None, 50.0],
            }
        )

    def test_case_when(self, numeric_ds):
        assert compliance(
            numeric_ds, "CASE WHEN x > 1 THEN 1 ELSE 0 END = 1"
        ) == 0.5
        # first matching branch wins
        assert compliance(
            numeric_ds,
            "CASE WHEN x >= 2 THEN 10 WHEN x >= 1 THEN 5 ELSE 0 END >= 5",
        ) == 0.75
        # no ELSE and no match -> NULL -> not compliant
        assert compliance(
            numeric_ds, "CASE WHEN x > 1 THEN 1 END = 1"
        ) == 0.5

    def test_case_when_null_condition_skips(self, strings_ds):
        # x NULL rows: condition is NULL -> falls to ELSE
        assert compliance(
            strings_ds, "CASE WHEN x > 2 THEN 1 ELSE 2 END = 2"
        ) == pytest.approx(3 / 5)

    def test_coalesce(self, strings_ds):
        # values: x=1 -> 1; x null -> y=20; x=3 -> 3; x=4 -> 4;
        # x null -> y=50; >= 3 passes on 4 of 5
        assert compliance(
            strings_ds, "COALESCE(x, y, 0) >= 3"
        ) == pytest.approx(4 / 5)
        assert compliance(
            strings_ds, "COALESCE(x, y, 0) >= 1"
        ) == 1.0

    def test_trim_upper_lower_substr(self, strings_ds):
        assert compliance(strings_ds, "TRIM(s) = 'Apple'") == 0.2
        assert compliance(strings_ds, "UPPER(s) = 'BANANA'") == 0.2
        assert compliance(strings_ds, "LOWER(TRIM(s)) = 'apple'") == 0.4
        assert compliance(strings_ds, "SUBSTR(TRIM(s), 1, 3) = 'App'") == 0.2
        assert compliance(strings_ds, "SUBSTRING(s, 1, 1) = 'b'") == 0.2
        assert compliance(strings_ds, "LENGTH(TRIM(s)) = 5") == 0.4
        assert compliance(strings_ds, "UPPER(s) LIKE 'A%'") == 0.2
        assert compliance(
            strings_ds, "LOWER(TRIM(s)) IN ('apple', 'banana')"
        ) == pytest.approx(3 / 5)
        # ordering over a transform (lexicographic ranks on the view)
        assert compliance(strings_ds, "LOWER(TRIM(s)) < 'b'") == 0.4

    def test_date_literals(self):
        import datetime

        ts = [
            datetime.datetime(2024, 1, 1),
            datetime.datetime(2024, 6, 15, 12, 30),
            datetime.datetime(2025, 1, 1),
            None,
        ]
        ds = Dataset.from_arrow(
            pa.table(
                {
                    "t": pa.array(ts, pa.timestamp("us")),
                    "d": pa.array(
                        [v.date() if v else None for v in ts], pa.date32()
                    ),
                }
            )
        )
        assert compliance(ds, "t >= '2024-06-01'") == 0.5
        assert compliance(ds, "t = '2024-06-15 12:30:00'") == 0.25
        assert compliance(ds, "'2024-12-31' < t") == 0.25
        assert compliance(ds, "d >= '2024-06-01'") == 0.5
        assert compliance(ds, "t BETWEEN '2024-01-01' AND '2024-12-31'") == 0.5

    def test_unsupported_degrade_to_failure_metric(self, strings_ds):
        for bad in (
            "DATE_ADD(s, 1) = 'yx'",  # unsupported function
            "TRIM(x) = 'a'",  # TRIM of numeric
            "CASE WHEN x > 0 THEN s ELSE 1 END = 1",  # mixed branches
            "COALESCE(s, 1) = 1",  # mixed branches
            "SUBSTR(s, x) = 'a'",  # non-static SUBSTR position
            "SUBSTR(s) = 'a'",  # wrong arity
            "TRIM(s, s) = 'a'",  # wrong arity
            "CASE WHEN s THEN 1 ELSE 0 END = 1",  # string condition
        ):
            metric = Compliance("t", bad).calculate(strings_ds)
            assert metric.value.is_failure, bad

    def test_bad_date_literal_degrades(self):
        import datetime

        ds = Dataset.from_arrow(
            pa.table(
                {
                    "t": pa.array(
                        [datetime.datetime(2024, 1, 1)], pa.timestamp("us")
                    )
                }
            )
        )
        metric = Compliance("t", "t >= 'not-a-date'").calculate(ds)
        assert metric.value.is_failure

    def test_bad_predicate_never_poisons_coscheduled_analyzers(self):
        """The module's core invariant: unsupported/malformed syntax
        fails at PLANNING time, degrading to THAT analyzer's failure
        metric — a co-scheduled analyzer in the same fused scan must
        come out clean (r4 review finding: date literals / string-fn
        arity / CASE conditions validated only at trace time poisoned
        the whole pass)."""
        import datetime

        from deequ_tpu.analyzers import AnalysisRunner

        ds = Dataset.from_arrow(
            pa.table(
                {
                    "x": pa.array([1.0, 2.0, 3.0]),
                    "s": pa.array(["a", "b", "a"]),
                    "t": pa.array(
                        [datetime.datetime(2024, 1, 1)] * 3,
                        pa.timestamp("us"),
                    ),
                }
            )
        )
        bads = [
            Compliance("bad-date", "t >= 'not-a-date'"),
            Compliance("bad-substr", "SUBSTR(s, x) = 'a'"),
            Compliance("bad-case", "CASE WHEN s THEN 1 ELSE 0 END = 1"),
            Compliance("bad-arity", "TRIM(s, s) = 'a'"),
        ]
        good = Mean("x")
        ctx = AnalysisRunner.do_analysis_run(ds, bads + [good])
        assert ctx.metric(good).value.is_success
        assert ctx.metric(good).value.get() == 2.0
        for bad in bads:
            assert ctx.metric(bad).value.is_failure, bad

    def test_partial_assertion_safe_on_filtered_domain(self):
        """A where-excluded row's value must not reach a row-level
        assertion (r4 review finding)."""
        from deequ_tpu import Check, CheckLevel, VerificationSuite

        ds = Dataset.from_pydict({"x": [1.0, 0.0, 2.0]})
        check = (
            Check(CheckLevel.ERROR, "partial")
            .has_min("x", lambda v: 1.0 / v > 0)
            .where("x != 0")
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        cols = [n for n in rl.schema.names if "Minimum" in n]
        assert cols, rl.schema.names  # column present, not dropped
        assert rl.column(cols[0]).to_pylist() == [True, True, True]

    def test_concat_and_cast(self, strings_ds):
        # CONCAT: one column + literals, composing with transforms
        assert compliance(
            strings_ds, "CONCAT('<', TRIM(s), '>') = '<banana>'"
        ) == 0.2
        assert compliance(
            strings_ds, "CONCAT(LOWER(s), '!') LIKE '%y!'"
        ) == 0.2  # CHERRY -> cherry!
        # CAST numeric
        assert compliance(strings_ds, "CAST(x AS INT) = 3") == 0.2
        assert compliance(
            strings_ds, "CAST(y / 3 AS INT) = 3"
        ) == 0.2  # 10/3 -> 3
        # CAST string column to number: parse per dictionary entry
        ds = Dataset.from_pydict(
            {"s": ["1.5", "2", "x", None, " 3 "]}
        )
        assert compliance(ds, "CAST(s AS DOUBLE) >= 1.5") == pytest.approx(
            3 / 5
        )
        assert compliance(ds, "CAST(s AS INT) = 1") == 0.2  # trunc(1.5)
        # unparseable -> NULL -> IS NULL sees it
        assert compliance(
            ds, "CAST(s AS DOUBLE) IS NULL"
        ) == pytest.approx(2 / 5)  # 'x' and the real null

    def test_cast_nan_entry_is_value_not_null(self):
        """Spark's cast('NaN' AS DOUBLE) yields the VALUE NaN, not
        NULL — validity must not be inferred from the parsed value
        being NaN (r4 advisory)."""
        ds = Dataset.from_pydict({"s": ["NaN", "1.0", "x", None]})
        # NaN is NOT NULL (only 'x' and the real null are)
        assert compliance(
            ds, "CAST(s AS DOUBLE) IS NULL"
        ) == pytest.approx(2 / 4)
        assert compliance(
            ds, "CAST(s AS DOUBLE) IS NOT NULL"
        ) == pytest.approx(2 / 4)
        # NaN compares FALSE (not NULL) against anything
        assert compliance(
            ds, "CAST(s AS DOUBLE) >= 0 OR CAST(s AS DOUBLE) < 0"
        ) == pytest.approx(1 / 4)
        # ... but a non-finite STRING has no integral parse: the INT
        # cast nulls it (review finding on the validity-table fix)
        ds2 = Dataset.from_pydict({"s": ["NaN", "Infinity", "1", None]})
        assert compliance(
            ds2, "CAST(s AS INT) IS NULL"
        ) == pytest.approx(3 / 4)
        assert compliance(
            ds2, "CAST(s AS DOUBLE) IS NULL"
        ) == pytest.approx(1 / 4)

    def test_cast_numeric_source_jvm_saturation(self):
        """Numeric-source integral casts follow JVM d2i like non-ANSI
        Spark: truncate, saturate at the target bounds, NaN -> 0 —
        never NULL (review finding)."""
        ds = Dataset.from_pydict(
            {"x": [float("nan"), float("inf"), -float("inf"), 3e9, 1.5]}
        )
        assert compliance(ds, "CAST(x AS INT) IS NOT NULL") == 1.0
        assert compliance(ds, "CAST(x AS INT) = 0") == 0.2  # NaN
        assert compliance(
            ds, "CAST(x AS INT) = 2147483647"
        ) == 0.4  # +inf and 3e9 both saturate
        assert compliance(
            ds, "CAST(x AS SMALLINT) = 32767"
        ) == 0.4
        assert compliance(ds, "CAST(x AS BIGINT) > 9000000000") == 0.2

    def test_concat_cast_plan_time_failures(self, strings_ds):
        from deequ_tpu.analyzers import AnalysisRunner

        bads = [
            Compliance("c1", "CONCAT('a', 'b') = 'ab'"),  # constant
            Compliance("c2", "CAST(x AS STRING) = '1'"),  # numeric op
            Compliance("c3", "CAST(x AS BANANA) = 1"),  # unknown type
        ]
        good = Mean("x")
        ctx = AnalysisRunner.do_analysis_run(strings_ds, bads + [good])
        assert ctx.metric(good).value.is_success
        for bad in bads:
            assert ctx.metric(bad).value.is_failure, bad

    def test_cast_review_regressions(self):
        from deequ_tpu.analyzers import AnalysisRunner
        import datetime

        # underscore numeric syntax is Python-only; Spark -> NULL
        ds = Dataset.from_pydict({"s": ["1_0", "10"]})
        assert compliance(ds, "CAST(s AS DOUBLE) = 10") == 0.5
        assert compliance(ds, "CAST(s AS DOUBLE) IS NULL") == 0.5
        # timestamp CAST yields epoch SECONDS (r5, Spark semantics);
        # DATE columns still refuse at plan time (Spark refuses
        # date -> numeric)
        epoch = int(
            datetime.datetime(
                2024, 1, 1, tzinfo=datetime.timezone.utc
            ).timestamp()
        )
        ts = Dataset.from_arrow(
            pa.table(
                {
                    "t": pa.array(
                        [datetime.datetime(2024, 1, 1)], pa.timestamp("us")
                    ),
                    "d": pa.array(
                        [datetime.date(2024, 1, 1)], pa.date32()
                    ),
                    "x": pa.array([1.0]),
                }
            )
        )
        assert compliance(ts, f"CAST(t AS BIGINT) = {epoch}") == 1.0
        assert compliance(
            ts, f"CAST(t AS DOUBLE) = {epoch}.0"
        ) == 1.0
        bad = Compliance("c", "CAST(d AS BIGINT) = 1")
        good = Mean("x")
        ctx = AnalysisRunner.do_analysis_run(ts, [bad, good])
        assert ctx.metric(bad).value.is_failure
        assert ctx.metric(good).value.is_success

    def test_date_arithmetic(self):
        import datetime

        ts = [
            datetime.datetime(2024, 1, 1, 23, 0),
            datetime.datetime(2024, 1, 10),
            datetime.datetime(2024, 2, 1),
            None,
        ]
        ds = Dataset.from_arrow(
            pa.table(
                {
                    "t": pa.array(ts, pa.timestamp("us")),
                    "d": pa.array(
                        [v.date() if v else None for v in ts], pa.date32()
                    ),
                }
            )
        )
        # DATE_ADD shifts by whole days in the column's unit
        assert compliance(ds, "DATE_ADD(t, 5) >= '2024-01-07'") == 0.5
        assert compliance(ds, "DATE_SUB(t, 9) < '2024-01-02'") == 0.5
        assert compliance(ds, "DATE_ADD(d, 31) >= '2024-02-01'") == 0.75
        # DATEDIFF: column vs literal, both directions, two columns
        assert compliance(ds, "DATEDIFF(t, '2024-01-01') = 9") == 0.25
        assert compliance(ds, "DATEDIFF('2024-02-01', t) = 31") == 0.25
        assert compliance(ds, "DATEDIFF(t, d) = 0") == 0.75  # same day
        # null rows are never compliant
        assert compliance(ds, "DATEDIFF(t, '2000-01-01') > 0") == 0.75

    def test_date_arithmetic_plan_time_failures(self):
        import datetime

        from deequ_tpu.analyzers import AnalysisRunner

        ds = Dataset.from_arrow(
            pa.table(
                {
                    "t": pa.array(
                        [datetime.datetime(2024, 1, 1)], pa.timestamp("us")
                    ),
                    "x": pa.array([1.0]),
                }
            )
        )
        bads = [
            Compliance("b1", "DATE_ADD(x, 1) > 0"),  # not a timestamp
            Compliance("b2", "DATE_ADD(t, x) > '2024-01-01'"),  # non-static
            Compliance("b3", "DATEDIFF('2024-01-01', '2024-01-02') = 1"),
            Compliance("b4", "DATEDIFF(t, 'nope') = 1"),  # bad literal
        ]
        good = Mean("x")
        ctx = AnalysisRunner.do_analysis_run(ds, bads + [good])
        assert ctx.metric(good).value.is_success
        for bad in bads:
            assert ctx.metric(bad).value.is_failure, bad

    def test_date_add_truncates_and_mixed_units_compare(self):
        """r4 review: DATE_ADD casts to DATE first (Spark), and
        timestamp[us] vs date32 comparisons normalize units instead of
        comparing raw epochs."""
        import datetime

        ts = [
            datetime.datetime(2024, 1, 1, 23, 0),
            datetime.datetime(2024, 1, 10, 5, 30),
            None,
        ]
        ds = Dataset.from_arrow(
            pa.table(
                {
                    "t": pa.array(ts, pa.timestamp("us")),
                    "d": pa.array(
                        [v.date() if v else None for v in ts], pa.date32()
                    ),
                }
            )
        )
        # Spark: date_add('2024-01-01 23:00', 6) = DATE '2024-01-07'
        assert compliance(ds, "DATE_ADD(t, 6) = '2024-01-07'") == pytest.approx(1 / 3)
        # timestamp vs date32 column: same calendar instant at midnight
        # only when the time-of-day is zero; d promotes to t's unit, so
        # t >= d holds for all real rows and t = d for none (both have
        # time parts)
        assert compliance(ds, "t >= d") == pytest.approx(2 / 3)
        assert compliance(ds, "t = d") == 0.0
        # day-valued DATE_ADD vs raw column (mixed per-day lanes)
        assert compliance(ds, "DATE_ADD(d, 1) > t") == pytest.approx(2 / 3)


class TestR5GrammarExtensions:
    """String-valued CASE/COALESCE, multi-column CONCAT, CAST to
    STRING, timestamp CAST (VERDICT r4 next #5 — the predicate
    grammar's documented remainder)."""

    @pytest.fixture
    def two_strings(self):
        return Dataset.from_pydict(
            {
                "a": ["x", "y", None, "w", "x"],
                "b": ["1", "2", "3", None, "1"],
                "n": [1.0, 2.0, 3.0, 4.0, None],
            }
        )

    def test_string_case(self, two_strings):
        # string results from different columns + literal branches
        assert compliance(
            two_strings,
            "CASE WHEN n >= 3 THEN a ELSE b END = 'x'",
        ) == 0.0  # rows 3,4: a in (None,'w'); rows 0,1: b in ('1','2'); row 5 n null -> b='1'
        # row 5's NULL condition skips the WHEN and falls to ELSE
        assert compliance(
            two_strings,
            "CASE WHEN n < 3 THEN b ELSE 'zzz' END = 'zzz'",
        ) == pytest.approx(3 / 5)
        # string CASE composes with LIKE / LENGTH / IN
        assert compliance(
            two_strings,
            "CASE WHEN n < 3 THEN a ELSE b END LIKE 'x%'",
        ) == pytest.approx(1 / 5)
        assert compliance(
            two_strings,
            "LENGTH(CASE WHEN n < 3 THEN 'long-string' ELSE b END) > 5",
        ) == pytest.approx(2 / 5)
        # no ELSE and no match -> NULL
        assert compliance(
            two_strings, "CASE WHEN n > 100 THEN a END IS NULL"
        ) == 1.0

    def test_string_coalesce(self, two_strings):
        assert compliance(
            two_strings, "COALESCE(a, b) = 'x'"
        ) == pytest.approx(2 / 5)
        assert compliance(
            two_strings, "COALESCE(a, b, 'none') IS NOT NULL"
        ) == 1.0
        assert compliance(
            two_strings, "COALESCE(a, '?') = '?'"
        ) == pytest.approx(1 / 5)
        # ordering over a coalesced lane (shared rank domain):
        # lane = [x, y, 3, w, x]; '3' < 'w' lexicographically
        assert compliance(
            two_strings, "COALESCE(a, b) >= 'w'"
        ) == pytest.approx(4 / 5)

    def test_multi_column_concat(self, two_strings):
        assert compliance(
            two_strings, "CONCAT(a, b) = 'x1'"
        ) == pytest.approx(2 / 5)
        # any null operand -> NULL (Spark concat)
        assert compliance(
            two_strings, "CONCAT(a, b) IS NULL"
        ) == pytest.approx(2 / 5)
        assert compliance(
            two_strings, "CONCAT(a, '-', b) = 'x-1'"
        ) == pytest.approx(2 / 5)
        # composes with transforms and string CASE
        assert compliance(
            two_strings, "CONCAT(UPPER(a), b) = 'X1'"
        ) == pytest.approx(2 / 5)
        assert compliance(
            two_strings,
            "CONCAT(a, CASE WHEN n < 2 THEN b ELSE 'z' END) = 'x1'",
        ) == pytest.approx(1 / 5)

    def test_cast_string(self, two_strings):
        import pyarrow as pa

        assert compliance(
            two_strings, "CAST(a AS STRING) = 'x'"
        ) == pytest.approx(2 / 5)
        assert compliance(
            two_strings, "CAST(UPPER(a) AS STRING) = 'X'"
        ) == pytest.approx(2 / 5)
        bools = Dataset.from_arrow(
            pa.table({"f": pa.array([True, False, None, True])})
        )
        assert compliance(bools, "CAST(f AS STRING) = 'true'") == 0.5
        assert compliance(
            bools, "CAST(f AS STRING) LIKE 'f%'"
        ) == 0.25

    def test_plan_time_failures_remain(self, two_strings):
        from deequ_tpu.analyzers import AnalysisRunner

        bads = [
            # heterogeneous branches
            Compliance("h1", "CASE WHEN n > 1 THEN a ELSE 1 END = 1"),
            Compliance("h2", "COALESCE(a, n) = 'x'"),
            # numeric formatting
            Compliance("h3", "CAST(n AS STRING) = '1'"),
            # arithmetic on a synthetic lane
            Compliance("h4", "CONCAT(a, b) + 1 > 0"),
        ]
        good = Mean("n")
        ctx = AnalysisRunner.do_analysis_run(two_strings, bads + [good])
        assert ctx.metric(good).value.is_success
        for bad in bads:
            assert ctx.metric(bad).value.is_failure, bad

    def test_concat_budget_enforced(self):
        from deequ_tpu.analyzers import AnalysisRunner

        big = [f"v{i}" for i in range(300)]
        ds = Dataset.from_pydict(
            {
                "a": [big[i % 300] for i in range(1000)],
                "b": [big[(i * 7) % 300] for i in range(1000)],
            }
        )
        # 300 x 300 = 90k > 65536 budget -> plan-time failure metric
        bad = Compliance("c", "CONCAT(a, b) = 'v1v1'")
        ctx = AnalysisRunner.do_analysis_run(ds, [bad])
        assert ctx.metric(bad).value.is_failure


class TestPredicateSoakSmoke:
    """Seeded slice of the randomized differential soak
    (tools/predicate_oracle.py): the compiled device path must agree
    with a host-side 3VL oracle on every row, over random expressions
    covering the full grammar incl. the r5 synthetic string lanes.
    The full soak (400+ exprs) runs manually; this guards the repo's
    largest file on every CI run."""

    def test_seeded_soak_slice(self):
        import os
        import sys

        sys.path.insert(
            0,
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        from tools.predicate_oracle import run_predicate_soak

        failures, skipped = run_predicate_soak(
            200, seed=7, n_rows=150, verbose=False
        )
        assert not failures, failures[:3]
        # the generator emits only supported grammar: any plan-time
        # rejection means generator and compiler disagree on coverage
        assert skipped == 0

    def test_boundary_fuzz_rejects_cleanly(self):
        """The flip side of the soak: deliberately-UNSUPPORTED grammar
        (unknown columns/functions, syntax junk, bad arity) through the
        full Compliance planning path. Every expression must land as a
        plan-time failure metric — no crash out of the runner, no
        silent success."""
        import os
        import sys

        sys.path.insert(
            0,
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        from tools.predicate_oracle import run_boundary_fuzz

        crashes, accepted = run_boundary_fuzz(
            120, seed=11, n_rows=60, verbose=False
        )
        assert crashes == [], crashes[:2]
        assert accepted == [], accepted[:5]


class TestR5GrammarIntegration:
    """The r5 grammar flows through the OTHER predicate consumers:
    row-level outcomes and Applicability."""

    def test_row_level_with_synthetic_lanes(self):
        from deequ_tpu import Check, CheckLevel, VerificationSuite

        ds = Dataset.from_pydict(
            {
                "a": ["x", None, "y"],
                "b": ["1", "2", None],
                "n": [1.0, 2.0, 3.0],
            }
        )
        check = Check(CheckLevel.ERROR, "rl").satisfies(
            "CONCAT(a, '-', b) = 'x-1' OR "
            "CASE WHEN n > 2 THEN a ELSE b END = 'y'",
            "syn",
            lambda v: v > 0,
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        col = rl.column(rl.schema.names[0]).to_pylist()
        # row0: concat 'x-1' T; row1: a null->concat NULL, case n<=2
        #   -> b='2' != 'y' F; row2: concat NULL, case n>2 -> a='y' T
        assert col == [True, False, True]

    def test_applicability_with_r5_grammar(self):
        from deequ_tpu import Check, CheckLevel
        from deequ_tpu.analyzers.applicability import Applicability

        ds = Dataset.from_pydict({"s": ["a"], "t": ["b"], "n": [1.0]})
        check = (
            Check(CheckLevel.ERROR, "app")
            .satisfies("CONCAT(s, t) != ''", "c1", lambda v: v >= 0)
            .satisfies(
                "COALESCE(s, 'z') = 'a' AND CAST(s AS STRING) <= 'b'",
                "c2",
                lambda v: v >= 0,
            )
        )
        report = Applicability().is_applicable(check, ds.schema)
        assert report.is_applicable, report
