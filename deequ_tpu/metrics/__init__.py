from deequ_tpu.metrics.metric import (
    DoubleMetric,
    Entity,
    KeyedDoubleMetric,
    Metric,
)
from deequ_tpu.metrics.distribution import (
    Distribution,
    DistributionValue,
    HistogramMetric,
)
from deequ_tpu.metrics.kll import BucketDistribution, BucketValue, KLLMetric

__all__ = [
    "BucketDistribution",
    "BucketValue",
    "Distribution",
    "DistributionValue",
    "DoubleMetric",
    "Entity",
    "HistogramMetric",
    "KeyedDoubleMetric",
    "KLLMetric",
    "Metric",
]
