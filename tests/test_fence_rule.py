"""fence-discipline staticcheck rule (tools/staticcheck/fence.py).

Fixture pattern matches tests/test_staticcheck.py: every behavior is
pinned by a PLANTED violation the analyzer must catch plus its
corrected twin it must stay silent on. The whole-repo cleanliness gate
lives in test_staticcheck's ``TestRepoGate`` — these tests only pin
the rule's own detection logic.
"""

import os
import textwrap

from tools.staticcheck import run_analyzers, unwaived

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, rel, source):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return rel


def _fence_findings(tmp_path):
    findings = unwaived(run_analyzers(str(tmp_path)))
    return [f for f in findings if f.rule == "fence-discipline"]


UNFENCED_TERMINAL = """
    def finish(self, handle):
        state, error = handle.terminal_info()
        self.journal.record_terminal(handle.run_id, state)
"""

FENCED_TERMINAL = """
    def finish(self, handle):
        if not epoch_fence_check(self.fleet):
            return
        state, error = handle.terminal_info()
        self.journal.record_terminal(handle.run_id, state)
"""


class TestFenceDiscipline:
    def test_catches_unfenced_journal_persist(self, tmp_path):
        _write(
            tmp_path, "deequ_tpu/service/fixture.py", UNFENCED_TERMINAL
        )
        findings = _fence_findings(tmp_path)
        assert len(findings) == 1
        assert findings[0].symbol == "record_terminal"
        assert "epoch_fence_check" in findings[0].message

    def test_silent_on_fenced_twin(self, tmp_path):
        _write(
            tmp_path, "deequ_tpu/service/fixture.py", FENCED_TERMINAL
        )
        assert _fence_findings(tmp_path) == []

    def test_catches_unfenced_repository_save(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def persist(repository, key, result, fleet=None):
                repository.save(result)
            """,
        )
        findings = _fence_findings(tmp_path)
        assert len(findings) == 1
        assert findings[0].symbol == "save"

    def test_fence_must_precede_lexically(self, tmp_path):
        """A fence check AFTER the persist does not license it — the
        ordering is the invariant, not mere presence."""
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def finish(self, handle):
                self.journal.record_terminal(handle.run_id, "done")
                if not epoch_fence_check(self.fleet):
                    return
            """,
        )
        assert len(_fence_findings(tmp_path)) == 1

    def test_each_function_needs_its_own_fence(self, tmp_path):
        """A fence in one function does not cover a persist in a
        sibling — every scope establishes its own (the fence is sticky
        per check, not per module)."""
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def fenced(self, handle):
                if not epoch_fence_check(self.fleet):
                    return
                self.journal.record_started(handle.run_id)

            def unfenced(self, handle):
                self.journal.record_started(handle.run_id)
            """,
        )
        findings = _fence_findings(tmp_path)
        assert len(findings) == 1
        assert "unfenced" in findings[0].message

    def test_every_guarded_record_attr_is_covered(self, tmp_path):
        guarded = (
            "record_submitted",
            "record_started",
            "record_checkpoint",
            "record_preempted",
            "record_resumed",
            "record_terminal",
        )
        body = "\n".join(
            f"    journal.{attr}('run-1')" for attr in guarded
        )
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            f"def persist_all(journal):\n{body}\n",
        )
        findings = _fence_findings(tmp_path)
        assert sorted(f.symbol for f in findings) == sorted(guarded)

    def test_super_save_definitions_are_exempt(self, tmp_path):
        """``super().save(...)`` has a computed callee (the func value
        is a Call), so checkpointer subclass DEFINITIONS that fence
        inside save() do not flag."""
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            class Fenced(Base):
                def save(self, cursor):
                    if child_epoch_fenced():
                        return
                    super().save(cursor)
            """,
        )
        assert _fence_findings(tmp_path) == []

    def test_out_of_scope_dirs_are_untouched(self, tmp_path):
        """The rule scopes to deequ_tpu/service/ — engine code calling
        .save() (checkpointers themselves) is not service persist
        discipline."""
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            UNFENCED_TERMINAL,
        )
        assert _fence_findings(tmp_path) == []

    def test_journal_module_itself_is_exempt(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/journal.py",
            """
            class RunJournal:
                def record_terminal(self, run_id, state):
                    return self.append("terminal", run_id, state=state)

                def helper(self):
                    self.record_terminal("r", "done")
            """,
        )
        assert _fence_findings(tmp_path) == []

    def test_waiver_suppresses_with_reason(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def adopt(self, journal, run_id):
                # lint-ok: fence-discipline: the lease CAS win one
                # line above IS the fence for this write
                journal.record_terminal(run_id, "adopted")
            """,
        )
        assert _fence_findings(tmp_path) == []

    def test_rule_registered_in_default_suite(self):
        from tools.staticcheck import all_rules

        assert "fence-discipline" in [rule for rule, _ in all_rules()]
