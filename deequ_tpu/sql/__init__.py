from deequ_tpu.sql.predicate import (
    CompiledPredicate,
    PredicateParseError,
    compile_predicate,
    parse_predicate,
)

__all__ = [
    "CompiledPredicate",
    "PredicateParseError",
    "compile_predicate",
    "parse_predicate",
]
