"""Vectorized group ops (engine/vectorize.py) must be bit-equivalent to
the per-analyzer scalar paths, and the default profile must carry
approx percentiles (SURVEY.md §3.3 pass 2)."""

import numpy as np
import pytest

from deequ_tpu import (
    ApproxCountDistinct,
    ApproxQuantiles,
    Completeness,
    DataType,
    Dataset,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.runner import AnalysisRunner
from deequ_tpu.engine.vectorize import plan_scan_units
from deequ_tpu.profiles.profiler import ColumnProfiler
from deequ_tpu.sketches.kll import KLLParameters


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(42)
    n = 5000
    a = rng.normal(10.0, 3.0, n)
    a[rng.integers(0, n, 200)] = np.nan
    import pyarrow as pa

    return Dataset.from_arrow(
        pa.table(
            {
                "a": pa.array(a, pa.float64(), mask=np.isnan(a)),
                "b": pa.array(rng.normal(-5, 1, n), pa.float64()),
                "k": pa.array(rng.integers(0, 500, n, dtype=np.int64)),
                "s": pa.array(
                    np.resize(
                        np.array(
                            ["ab", "c", None, "12", "3.5", "true"],
                            dtype=object,
                        ),
                        n,
                    )
                ),
            }
        )
    )


ANALYZERS = [
    Mean("a"), Sum("a"), Minimum("a"), Maximum("a"), StandardDeviation("a"),
    Mean("b"), Sum("b"), Minimum("b"), Maximum("b"), StandardDeviation("b"),
    Mean("k"), Minimum("k"), Maximum("k"),
    Completeness("a"), Completeness("b"), Completeness("s"),
    ApproxCountDistinct("a"), ApproxCountDistinct("b"),
    ApproxCountDistinct("k"), ApproxCountDistinct("s"),
    DataType("s"), MinLength("s"), MaxLength("s"),
    ApproxQuantiles("a", (0.25, 0.5, 0.75)),
    ApproxQuantiles("b", (0.25, 0.5, 0.75)),
]


def test_planner_groups_families(ds):
    units, failures = plan_scan_units(ds, ANALYZERS)
    assert not failures
    # far fewer units than analyzers: stats f64, stats i64, completeness,
    # hll f64, hll i64, hll codes, datatype, lengths, kll + singles
    assert len(units) < len(ANALYZERS) / 2
    grouped = [u for u in units if u.extract is not None]
    assert sum(len(u.members) for u in grouped) >= 20


def test_vectorized_equals_individual(ds):
    ctx = AnalysisRunner.do_analysis_run(ds, ANALYZERS)
    # individual path: plan each analyzer alone (no grouping possible)
    for analyzer in ANALYZERS:
        solo = AnalysisRunner.do_analysis_run(ds, [analyzer])
        grouped_metric = ctx.metric(analyzer)
        solo_metric = solo.metric(analyzer)
        assert grouped_metric.value.is_success, repr(analyzer)
        gv, sv = grouped_metric.value.get(), solo_metric.value.get()
        if isinstance(gv, dict):
            assert gv.keys() == sv.keys()
            for key in gv:
                assert gv[key] == pytest.approx(sv[key], rel=1e-12), (
                    analyzer,
                    key,
                )
        elif isinstance(gv, float):
            assert gv == pytest.approx(sv, rel=1e-12), repr(analyzer)
        else:  # distributions
            assert gv == sv, repr(analyzer)


def test_kll_group_shares_sketch_per_column(ds):
    params = KLLParameters()
    units, _ = plan_scan_units(
        ds, [KLLSketch("a", params), ApproxQuantiles("a", (0.5,), params=params)]
    )
    kll_units = [u for u in units if u.extract is not None]
    assert len(kll_units) == 1
    assert len(kll_units[0].members) == 2
    # one column slot shared by both members
    state = kll_units[0].ops.host_init()
    assert len(state) == 1


def test_default_profile_has_percentiles(ds):
    profiles = ColumnProfiler.profile(ds)
    prof = profiles["a"]
    assert prof.approx_percentiles is not None
    assert len(prof.approx_percentiles) == 99
    # median of N(10, 3) with nulls skipped: near 10
    assert prof.approx_percentiles[49] == pytest.approx(10.0, abs=0.5)
    assert profiles["k"].approx_percentiles is not None
    # string column has no percentiles
    assert getattr(profiles["s"], "approx_percentiles", None) is None


def test_group_states_persist_and_merge(ds, tmp_path):
    from deequ_tpu import FileSystemStateProvider

    half = ds.num_rows // 2
    mask1 = np.zeros(ds.num_rows, dtype=bool)
    mask1[:half] = True
    d1, d2 = ds.filter_rows(mask1), ds.filter_rows(~mask1)
    p1 = FileSystemStateProvider(str(tmp_path / "s1"))
    p2 = FileSystemStateProvider(str(tmp_path / "s2"))
    AnalysisRunner.do_analysis_run(d1, ANALYZERS, save_states_with=p1)
    AnalysisRunner.do_analysis_run(d2, ANALYZERS, save_states_with=p2)
    merged = AnalysisRunner.run_on_aggregated_states(
        ds.schema, ANALYZERS, [p1, p2]
    )
    union = AnalysisRunner.do_analysis_run(ds, ANALYZERS)
    for analyzer in ANALYZERS:
        mv = merged.metric(analyzer).value
        uv = union.metric(analyzer).value
        assert mv.is_success, repr(analyzer)
        m, u = mv.get(), uv.get()
        # sketches (KLL quantiles, HLL) merge within their error bounds,
        # not bit-identically; everything else must match exactly
        sketchy = type(analyzer).__name__ in (
            "ApproxQuantiles",
            "ApproxQuantile",
            "ApproxCountDistinct",
            "KLLSketch",
        )
        rel = 2e-2 if sketchy else 1e-9
        if isinstance(m, dict):
            for key in m:
                assert m[key] == pytest.approx(u[key], rel=rel, abs=0.2), (
                    analyzer,
                    key,
                )
        elif isinstance(m, float):
            assert m == pytest.approx(u, rel=rel), repr(analyzer)


def test_presence_path_equals_gather_path(ds, monkeypatch):
    """For dict-encoded columns the presence compare-reduce path (small
    dictionaries) must produce bit-identical HLL registers and DataType
    counts to the per-row gather+scatter path (r4 perf work — the two
    share states/merge, so divergence would corrupt max-merges).

    The plan runs TWO string columns per family so the STACKED group
    builders' presence branches execute (single-member groups demote to
    the single-analyzer builders), plus a where-variant that stays a
    single: both implementations are pinned against the gather path."""
    import pyarrow as pa

    from deequ_tpu.engine import scan as scan_mod
    from deequ_tpu.sketches import hll as hll_mod

    rng = np.random.default_rng(7)
    n = 4000
    two_strings = Dataset.from_arrow(
        pa.table(
            {
                "s1": pa.array(
                    np.resize(
                        np.array(
                            ["ab", "c", None, "12", "3.5", "true"],
                            dtype=object,
                        ),
                        n,
                    )
                ),
                "s2": pa.array(
                    rng.choice(["x", "7", "2.5", "false", "yy"], n)
                ),
                "k": pa.array(rng.integers(0, 500, n, dtype=np.int64)),
            }
        )
    )
    plan = [
        ApproxCountDistinct("s1"),
        ApproxCountDistinct("s2"),
        DataType("s1"),
        DataType("s2"),
        ApproxCountDistinct("s1", where="k > 100"),
    ]

    def run():
        scan_mod._PLAN_CACHE.clear()  # cached closures pin the old path
        units, _ = plan_scan_units(two_strings, plan)
        ctx = AnalysisRunner.do_analysis_run(two_strings, plan)
        out = {}
        for a in plan:
            m = ctx.metric(a)
            assert m.value.is_success, (a, m.value)
            v = m.value.get()
            out[repr(a)] = (
                {k: d.absolute for k, d in v.values.items()}
                if hasattr(v, "values")
                else v
            )
        return out, len(units)

    fast, n_units = run()
    # the two-column families must actually have grouped (stacked path)
    assert n_units == 3  # hll(s1,s2) + datatype(s1,s2) + where-single
    monkeypatch.setattr(hll_mod, "PRESENCE_DICT_CAP", 0)  # force gather
    slow, _ = run()
    scan_mod._PLAN_CACHE.clear()
    assert fast == slow
