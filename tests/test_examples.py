"""Every runnable example executes green in the suite (VERDICT r4
missing #5: the reference's examples at least compile with the build —
ours must RUN, so a signature drift in the public API fails loudly
here instead of shipping silently).

Each example's ``main()`` runs in-process on the suite's 8-virtual-
device CPU backend (conftest).  ``multihost_profiling``,
``multihost_grouping`` and ``distributed_service`` are excluded HERE
only because ``tests/test_multihost.py`` already executes them as
two-real-process subprocess runs — together the suite runs every
example."""

import importlib
import os
import sys

import pytest

_EXAMPLES_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)

# every example EXCEPT multihost_profiling (run by test_multihost.py)
_IN_PROCESS = [
    "anomaly_detection",
    "basic_verification",
    "high_cardinality_and_warehouse",
    "incremental_metrics",
    "mesh_execution",
    "production_pipeline",
    "profiling_and_suggestion",
    "rowlevel_quarantine",
    "verification_service",
]


def _all_examples() -> set:
    return {
        f[: -len(".py")]
        for f in os.listdir(_EXAMPLES_DIR)
        if f.endswith(".py")
    }


def test_every_example_is_covered():
    """A new example file must be added to _IN_PROCESS (or get its own
    dedicated test like the multihost pair has)."""
    assert _all_examples() == set(_IN_PROCESS) | {
        "multihost_profiling",
        "multihost_grouping",
        "distributed_service",
    }


@pytest.mark.parametrize("name", _IN_PROCESS)
def test_example_runs(name, tmp_path, monkeypatch):
    # examples that write artifacts do so relative to cwd or tempdirs;
    # isolate cwd so suite runs never litter the repo root
    monkeypatch.chdir(tmp_path)
    sys.path.insert(0, _EXAMPLES_DIR)
    try:
        module = importlib.import_module(name)
        module.main()
    finally:
        sys.path.remove(_EXAMPLES_DIR)
