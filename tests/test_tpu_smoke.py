"""Real-accelerator smoke test (VERDICT r2 weak #7): the ONLY thing
exercising TPU lowering between rounds used to be bench.py. This test
runs a fixed analyzer set in a subprocess on the DEFAULT jax backend
(the real chip when present) and asserts metric equality against the
in-process forced-CPU run — catching dtype/lowering drift before the
bench does. Skips cleanly when no accelerator backend exists."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_SCRIPT = r"""
import json
import sys

import jax

if jax.default_backend() in ("cpu",):
    print("SKIP:no-accelerator")
    sys.exit(0)

import numpy as np

from deequ_tpu import Dataset
from deequ_tpu.analyzers import (
    AnalysisRunner, ApproxCountDistinct, Completeness, Compliance,
    CountDistinct, Maximum, Mean, Minimum, MinLength, StandardDeviation,
    Sum, Uniqueness,
)

rng = np.random.default_rng(42)
n = 100_000
x = rng.normal(50.0, 9.0, n).astype(object)
x[::13] = None
ds = Dataset.from_pydict({
    "x": list(x),
    "k": list(rng.integers(0, 30_000, n, dtype=np.int64)),
    "s": list(np.array(["aa", "bb", "ccc"])[rng.integers(0, 3, n)]),
})
analyzers = [
    Mean("x"), Sum("x"), Minimum("x"), Maximum("x"),
    StandardDeviation("x"), Completeness("x"),
    Compliance("pos", "x > 50"), MinLength("s"),
    ApproxCountDistinct("k"), CountDistinct("k"), Uniqueness("k"),
]
ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
out = {}
for a in analyzers:
    v = ctx.metric(a).value
    out[f"{a.name}:{a.instance}"] = v.get() if v.is_success else None
print("RESULT:" + json.dumps(out))
"""


def test_default_backend_metrics_equal_cpu():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # undo the conftest's CPU forcing for the child: fresh process, no
    # XLA_FLAGS override, default platform (axon/TPU when present)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = ""
    try:
        result = subprocess.run(
            [sys.executable, "-c", _SCRIPT],
            capture_output=True,
            text=True,
            # a healthy chip finishes in well under this; a FLAKY
            # accelerator tunnel can hang the child's backend init for
            # many minutes — degrade to the no-accelerator skip instead
            # of eating the whole tier-1 wall budget
            timeout=300,
            env=env,
            cwd=repo,
        )
    except subprocess.TimeoutExpired:
        pytest.skip(
            "accelerator backend unreachable (child backend init "
            "exceeded 300s — flaky tunnel)"
        )
    assert result.returncode == 0, result.stdout + result.stderr
    if "SKIP:no-accelerator" in result.stdout:
        pytest.skip("no accelerator backend in this environment")
    line = [
        ln for ln in result.stdout.splitlines() if ln.startswith("RESULT:")
    ]
    assert line, result.stdout + result.stderr
    device_metrics = json.loads(line[0][len("RESULT:"):])

    # the same computation on the forced-CPU in-process backend
    from deequ_tpu import Dataset
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        ApproxCountDistinct,
        Completeness,
        Compliance,
        CountDistinct,
        Maximum,
        Mean,
        Minimum,
        MinLength,
        StandardDeviation,
        Sum,
        Uniqueness,
    )

    rng = np.random.default_rng(42)
    n = 100_000
    x = rng.normal(50.0, 9.0, n).astype(object)
    x[::13] = None
    ds = Dataset.from_pydict(
        {
            "x": list(x),
            "k": list(rng.integers(0, 30_000, n, dtype=np.int64)),
            "s": list(np.array(["aa", "bb", "ccc"])[rng.integers(0, 3, n)]),
        }
    )
    analyzers = [
        Mean("x"), Sum("x"), Minimum("x"), Maximum("x"),
        StandardDeviation("x"), Completeness("x"),
        Compliance("pos", "x > 50"), MinLength("s"),
        ApproxCountDistinct("k"), CountDistinct("k"), Uniqueness("k"),
    ]
    ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
    for a in analyzers:
        key = f"{a.name}:{a.instance}"
        want = ctx.metric(a).value.get()
        got = device_metrics[key]
        assert got is not None, key
        # counts/ratios are exact; float accumulations may differ at
        # reduction-order noise level across backends
        assert got == pytest.approx(want, rel=1e-6, abs=1e-9), (
            key, got, want,
        )
