"""The multi-tenant verification service, end to end (PR 7
acceptance):

1. start a ``VerificationService`` (2 workers, 1 interactive reserve)
   and warm the EXACT production suites at startup (tools/warmup.py:
   compiles key on structure/shapes, never values, so synthetic data
   with the production schema warms the production plans);
2. drive FOUR concurrent clients across TWO tenants with mixed
   priorities against ONE shared dataset key — the telemetry must show
   **zero plan recompiles** after warmup and **one dataset placement**
   total (three cache hits share the resident handle);
3. the interactive reserve keeps the risk tenant's short run ahead of
   the analytics tenant's parked batch run (no priority inversion);
4. resubmission: the same suite runs again and the warm plan survives
   (still zero compiles);
5. a ``hll_dedup_widening`` flag flip compiles under a DISTINCT
   plan-cache entry — engine options are part of the plan fingerprint,
   so a flipped production run never poisons the warm cache;
6. the JSONL telemetry artifact renders the operator's ``service:``
   section (tools/obs_report.py).

Run: python examples/verification_service.py
"""

import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deequ_tpu import (  # noqa: E402
    Check,
    CheckLevel,
    CheckStatus,
    Dataset,
    config,
    telemetry,
)
from deequ_tpu.service import (  # noqa: E402
    Priority,
    RunRequest,
    VerificationService,
)

ROWS = 20_000
SCHEMA = {"order_id": "int64", "txn_hash": "int64", "amount": "float32"}
DATASET_KEY = "warehouse/orders"


def make_orders() -> Dataset:
    """THE shared table: every tenant's runs verify this one key, so
    the service's dataset cache places it on device exactly once."""
    rng = np.random.default_rng(42)
    return Dataset.from_pydict(
        {
            # wide int64s (beyond the f32-exact range): the schema
            # shape whose pooled-HLL unit the widening flag changes
            "order_id": rng.integers(0, 1 << 40, ROWS, dtype=np.int64),
            "txn_hash": rng.integers(0, 1 << 40, ROWS, dtype=np.int64),
            "amount": np.abs(
                rng.normal(40.0, 12.0, ROWS)
            ).astype(np.float32),
        }
    )


def batch_checks():
    """The analytics tenant's heavier nightly suite."""
    return [
        Check(CheckLevel.ERROR, "orders-nightly")
        .is_complete("order_id")
        .is_unique("order_id")
        .is_unique("txn_hash")
        .is_complete("amount")
        .is_non_negative("amount")
    ]


def interactive_checks():
    """The risk tenant's short pre-trade gate."""
    return [
        Check(CheckLevel.ERROR, "orders-gate")
        .is_complete("amount")
        .is_non_negative("amount")
    ]


def main() -> None:
    jsonl = os.path.abspath("service_telemetry.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    telemetry.configure(jsonl_path=jsonl)
    tm = telemetry.get_telemetry()

    svc = VerificationService(workers=2, interactive_reserve=1).start()

    # -- startup warmup: the exact suites production will submit ------
    warm_kwargs = dict(
        profile=False,
        nullable=(False,),
        wide_ints=(True,),
        batch_size=ROWS,  # engines resolve batch = min(rows, default)
        engine_variants=[{}],
    )
    tokens = svc.warmup(SCHEMA, checks=batch_checks(), **warm_kwargs)
    tokens += svc.warmup(
        SCHEMA, checks=interactive_checks(), **warm_kwargs
    )
    print(f"warmed {len(tokens)} plan token(s): {', '.join(tokens)}")

    compiles_before = tm.counter("engine.plan_cache.misses").value
    placements_before = tm.counter("service.dataset_cache.misses").value
    shares_before = tm.counter("service.dataset_cache.hits").value

    # -- four concurrent clients, two tenants, mixed priorities -------
    def request(tenant, priority, checks):
        return RunRequest(
            tenant=tenant,
            checks=checks,
            dataset_key=DATASET_KEY,
            dataset_factory=make_orders,
            priority=priority,
        )

    results = {}
    results_lock = threading.Lock()

    def client(name, handle):
        res = handle.result(timeout=300)
        with results_lock:
            results[name] = (handle, res)

    # the analytics tenant's two batch runs go in first: one occupies
    # the single general worker, the second parks in the queue
    batch_handles = [
        svc.submit(request(
            "analytics", Priority.BATCH, batch_checks()
        ))
        for _ in range(2)
    ]
    # the risk tenant's interactive runs arrive LAST yet run on the
    # reserve worker immediately — the anti-starvation guarantee
    inter_handles = [
        svc.submit(request(
            "risk", Priority.INTERACTIVE, interactive_checks()
        ))
        for _ in range(2)
    ]
    threads = [
        threading.Thread(target=client, args=(f"client-{i}", h))
        for i, h in enumerate(batch_handles + inter_handles)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)

    for name, (handle, res) in sorted(results.items()):
        wait_s = handle.started_at - handle.submitted_at
        print(
            f"  {name}: tenant={handle.tenant} "
            f"priority={Priority.name(handle.priority)} "
            f"status={res.status.value} queue_wait={wait_s:.3f}s"
        )
    assert len(results) == 4
    assert all(
        res.status == CheckStatus.SUCCESS
        for _h, res in results.values()
    )

    # no priority inversion: both interactive runs started before the
    # parked batch run got the general worker back
    parked = max(batch_handles, key=lambda h: h.started_at)
    for h in inter_handles:
        assert h.started_at < parked.started_at, (
            "interactive run waited behind a batch run"
        )

    compiles = tm.counter("engine.plan_cache.misses").value
    placements = tm.counter("service.dataset_cache.misses").value
    shares = tm.counter("service.dataset_cache.hits").value
    print(f"recompiles after warmup: {compiles - compiles_before}")
    print(
        f"dataset placements: {placements - placements_before} "
        f"(shared leases: {shares - shares_before})"
    )
    assert compiles - compiles_before == 0, "steady state recompiled"
    assert placements - placements_before == 1, "dataset placed twice"
    assert shares - shares_before == 3

    # -- resubmission: the warm plan survives -------------------------
    again = svc.submit(request(
        "risk", Priority.INTERACTIVE, interactive_checks()
    ))
    assert again.result(timeout=300).status == CheckStatus.SUCCESS
    assert tm.counter("engine.plan_cache.misses").value == compiles
    print("resubmission reused the warm plan (0 new compiles)")

    # -- flag flip => distinct plan-cache entry -----------------------
    from deequ_tpu.engine.scan import plan_cache_snapshot
    from deequ_tpu.profiles.profiler import ColumnProfiler

    dataset, _hit = svc.datasets.lease(DATASET_KEY, make_orders)
    try:
        before_flip = set(plan_cache_snapshot())
        ColumnProfiler.profile(dataset)
        mid_flip = set(plan_cache_snapshot())
        with config.configure(hll_dedup_widening=False):
            ColumnProfiler.profile(dataset)
        after_flip = set(plan_cache_snapshot())
    finally:
        svc.datasets.release(DATASET_KEY)
    flipped_new = after_flip - mid_flip - before_flip
    assert flipped_new, "flag flip did not produce a distinct plan"
    print(
        f"hll_dedup_widening flip compiled {len(flipped_new)} distinct "
        f"plan entr{'ies' if len(flipped_new) > 1 else 'y'}"
    )

    svc.stop(drain=True)

    # -- scan coalescing: one superset scan, many tenants -------------
    # (docs/SERVICE.md "Scan coalescing") — a separate service with
    # coalescing ON: three tenants' overlapping BATCH suites against
    # the shared key are absorbed into ONE traversal. Submitting before
    # start() makes the grouping deterministic: the first worker pop
    # atomically takes the host ticket and every compatible peer.
    passes_before = tm.counter("engine.data_passes").value
    co = VerificationService(
        workers=2, interactive_reserve=1,
        coalesce=True, coalesce_window_s=0.0,
    )
    co_handles = [
        co.submit(RunRequest(
            tenant=tenant, checks=checks, dataset_key=DATASET_KEY,
            dataset_factory=make_orders, priority=Priority.BATCH,
        ))
        for tenant, checks in [
            ("analytics", batch_checks()),
            ("risk", interactive_checks()),
            ("audit", batch_checks()),
        ]
    ]
    co.start()
    co_results = [h.result(timeout=300) for h in co_handles]
    co.stop(drain=True)
    co_passes = tm.counter("engine.data_passes").value - passes_before
    saved = tm.counter("service.scan_passes_saved").value
    print(
        f"coalescing: {len(co_handles)} tenant runs in {co_passes} "
        f"data pass(es) ({saved} pass(es) saved)"
    )
    assert all(
        r.status == CheckStatus.SUCCESS for r in co_results
    )
    assert co_passes == 1, "coalesced group re-scanned the source"

    # -- the operator's report off the JSONL artifact -----------------
    from tools.obs_report import render_service

    section = render_service(telemetry.read_jsonl(jsonl))
    assert section.startswith("service:")
    print()
    print(section)
    telemetry.configure(jsonl_path=None)
    print()
    print("service demo OK: zero recompiles after warmup, "
          "one dataset placement, no priority inversion")


if __name__ == "__main__":
    main()
