"""Cross-host high-cardinality grouping over loopback: the TPU-native
shuffle spanning PROCESSES (docs/MULTIHOST.md steps 1-4; SURVEY §7
hard part #1 extended across hosts).

Two real processes (4 virtual CPU devices each) initialize
``jax.distributed`` against a loopback coordinator and build ONE global
8-device mesh. Each process reads ITS OWN parquet shard of a 10M-row,
~10M-distinct int64 key column — no host ever sees the other's rows —
and the bucketed ``all_to_all`` shuffle + per-shard sort + segment
count (analyzers/spill.multihost_spill_frequencies) computes
CountDistinct / Uniqueness / Distinctness / Entropy / Histogram with
NO host-side Arrow fallback and no cross-host group-state merge: equal
keys land on one device wherever their rows lived, and the count
scalars psum into replicated values.

The parent process then recomputes the same metrics over the WHOLE
table with the device spill disabled (the host Arrow ground truth) and
asserts equality.

    python examples/multihost_grouping.py
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_ROWS = 10_000_000
N_F64_ROWS = 2_000_000
N_OVERFLOW_ROWS = 200_000
TOP_K = 12

WORKER = r"""
import json, sys
import numpy as np
coordinator, pid, shard_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
f64_path, overflow_path = sys.argv[4], sys.argv[5]
import os
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=2, process_id=int(pid)
)
from jax.sharding import Mesh

from deequ_tpu import Dataset
from deequ_tpu.analyzers.grouping import FrequencyPlan
from deequ_tpu.analyzers import spill as spill_mod
from deequ_tpu.analyzers.spill import (
    SpillOverflow, multihost_spill_frequencies,
)
from deequ_tpu.analyzers import (
    CountDistinct, Distinctness, Entropy, Histogram, Uniqueness,
)

dataset = Dataset.from_parquet(shard_path)
mesh = Mesh(np.array(jax.devices()), ("dp",))

# count-family metrics share ONE shuffle (include_nulls=False);
# Histogram keeps its null bin via a second plan — exactly the
# single-host planner's split
count_state = multihost_spill_frequencies(
    dataset, FrequencyPlan(("k",), None, False), mesh
)
hist_state = multihost_spill_frequencies(
    dataset, FrequencyPlan(("k",), None, True), mesh
)
# where-filters evaluate per row on each host's OWN shard before the
# shuffle (r5): the filtered count must equal the whole-table filtered
# run too
where_state = multihost_spill_frequencies(
    dataset, FrequencyPlan(("k",), "k % 2 = 0", False), mesh
)

out = {}
for a in (CountDistinct("k"), Uniqueness("k"), Distinctness("k"),
          Entropy("k")):
    m = a.compute_metric_from_state(count_state)
    assert m.value.is_success, (a, m.value)
    out[a.name] = m.value.get()
m = CountDistinct("k", where="k % 2 = 0").compute_metric_from_state(
    where_state
)
assert m.value.is_success, m.value
out["CountDistinct_where"] = m.value.get()
hist = Histogram("k", max_detail_bins=TOPK).compute_metric_from_state(
    hist_state
)
assert hist.value.is_success, hist.value
dist = hist.value.get()
out["histogram"] = {
    str(k): v.absolute for k, v in dist.values.items()
}
out["histogram_bins"] = dist.number_of_bins
if int(pid) == 0:
    print("METRICS " + json.dumps(out), flush=True)

# ---- scenario 2: f64 keys, host-packed canonical bits --------------
# the same coordinator pair (no second jax.distributed init) runs the
# shuffle over an f64 key column with the host bit-packing forced —
# the path a TPU backend takes (its X64 rewriter cannot lower the f64
# bitcast), exercised here on CPU via the test hook
f64_ds = Dataset.from_parquet(f64_path)
spill_mod._FORCE_HOST_F64_BITS = True
try:
    f64_state = multihost_spill_frequencies(
        f64_ds, FrequencyPlan(("k",), None, False), mesh
    )
finally:
    spill_mod._FORCE_HOST_F64_BITS = False
f64_out = {}
for a in (CountDistinct("k"), Uniqueness("k"), Distinctness("k")):
    m = a.compute_metric_from_state(f64_state)
    assert m.value.is_success, (a, m.value)
    f64_out[a.name] = m.value.get()
if int(pid) == 0:
    print("F64_METRICS " + json.dumps(f64_out), flush=True)

# ---- scenario 3: forced SpillOverflow -> host Arrow fallback -------
# a constant key column: every row of every device hashes to ONE
# bucket, blowing past the static per-bucket capacity — SpillOverflow
# must raise UNIFORMLY on both hosts (never a one-sided hang), and the
# host Arrow fallback (local shard counts + one tiny allgather) still
# produces exact frequencies
ov_ds = Dataset.from_parquet(overflow_path)
try:
    multihost_spill_frequencies(
        ov_ds, FrequencyPlan(("c",), None, False), mesh
    )
    raise AssertionError("expected SpillOverflow on the constant key")
except SpillOverflow:
    pass
# fallback: exact local counts, merged with one scalar allgather
from jax.experimental import multihost_utils
vals = np.asarray(ov_ds.table.column("c").to_pylist(), dtype=np.int64)
uniq, counts = np.unique(vals, return_counts=True)
assert len(uniq) == 1
merged = np.asarray(multihost_utils.process_allgather(
    jax.numpy.asarray([int(counts[0])], dtype=jax.numpy.int64)
)).reshape(-1)
if int(pid) == 0:
    print("OVERFLOW_FALLBACK " + json.dumps({
        "key": int(uniq[0]), "total": int(merged.sum()),
    }), flush=True)
print(f"worker {pid} done", flush=True)
""".replace("TOPK", str(TOP_K))


def main() -> None:
    import shutil

    workdir = tempfile.mkdtemp(prefix="deequ_tpu_mh_grouping_")
    try:
        _run(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _run(workdir: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(8)
    keys = rng.integers(0, 1 << 40, N_ROWS, dtype=np.int64).astype(object)
    keys[::101] = None  # Histogram's null bin must survive the shuffle
    # a few heavy hitters so the top-k histogram is deterministic
    for rank, (value, count) in enumerate(
        [(7, 90_000), (11, 70_000), (13, 50_000), (1 << 39, 30_000)]
    ):
        lo = 1000 + rank * 200_000
        keys[lo : lo + count] = value
    table = pa.table({"k": pa.array(list(keys), pa.int64())})

    # UNEQUAL shards: 60% / 40%
    split = int(N_ROWS * 0.6)
    shards = []
    for i, (off, length) in enumerate(
        [(0, split), (split, N_ROWS - split)]
    ):
        path = os.path.join(workdir, f"shard{i}")
        os.makedirs(path, exist_ok=True)
        pq.write_table(
            table.slice(off, length),
            os.path.join(path, "part0.parquet"),
        )
        shards.append(path)

    # f64 scenario: wide-exponent doubles (incl. negatives and exact
    # duplicates) so the canonical-bit packing's total order matters
    f64_keys = np.round(rng.normal(0, 1e6, N_F64_ROWS), 3)
    f64_keys[:: 7] = 42.125  # heavy duplicate
    f64_table = pa.table({"k": pa.array(f64_keys, pa.float64())})
    f64_split = int(N_F64_ROWS * 0.6)
    f64_shards = []
    for i, (off, length) in enumerate(
        [(0, f64_split), (f64_split, N_F64_ROWS - f64_split)]
    ):
        path = os.path.join(workdir, f"f64shard{i}")
        os.makedirs(path, exist_ok=True)
        pq.write_table(
            f64_table.slice(off, length),
            os.path.join(path, "part0.parquet"),
        )
        f64_shards.append(path)

    # overflow scenario: a CONSTANT key — every row hashes to one
    # bucket, guaranteeing SpillOverflow at any realistic capacity
    ov_shards = []
    for i, length in enumerate(
        [N_OVERFLOW_ROWS // 2, N_OVERFLOW_ROWS - N_OVERFLOW_ROWS // 2]
    ):
        path = os.path.join(workdir, f"ovshard{i}")
        os.makedirs(path, exist_ok=True)
        pq.write_table(
            pa.table({"c": pa.array([7] * length, pa.int64())}),
            os.path.join(path, "part0.parquet"),
        )
        ov_shards.append(path)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, coordinator, str(i),
             shards[i], f64_shards[i], ov_shards[i]],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        for i in range(2)
    ]
    import time as _time

    deadline = _time.monotonic() + 600
    outputs = [b"", b""]
    try:
        for i, p in enumerate(procs):
            try:
                outputs[i], _ = p.communicate(
                    timeout=max(1.0, deadline - _time.monotonic())
                )
            except subprocess.TimeoutExpired:
                pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for i, p in enumerate(procs):
            if p.poll() is None or not outputs[i]:
                try:
                    extra, _ = p.communicate(timeout=10)
                    outputs[i] = outputs[i] + (extra or b"")
                except Exception:  # noqa: BLE001 — reporting only
                    pass
    failed = [i for i, p in enumerate(procs) if p.returncode != 0]
    if failed:
        report = "\n".join(
            f"--- worker {i} (rc={procs[i].returncode}) ---\n"
            + outputs[i].decode(errors="replace")
            for i in range(2)
        )
        raise RuntimeError(f"worker(s) {failed} failed:\n{report}")

    got = got_f64 = got_overflow = None
    for line in outputs[0].decode().splitlines():
        if line.startswith("METRICS "):
            got = json.loads(line[len("METRICS "):])
        elif line.startswith("F64_METRICS "):
            got_f64 = json.loads(line[len("F64_METRICS "):])
        elif line.startswith("OVERFLOW_FALLBACK "):
            got_overflow = json.loads(line[len("OVERFLOW_FALLBACK "):])
    assert got is not None, outputs[0].decode()
    assert got_f64 is not None, outputs[0].decode()
    assert got_overflow is not None, outputs[0].decode()

    # ground truth: whole table, device spill DISABLED (host Arrow)
    from deequ_tpu import Dataset, config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        CountDistinct,
        Distinctness,
        Entropy,
        Histogram,
        Uniqueness,
    )

    whole = Dataset.from_arrow(table)
    analyzers = [
        CountDistinct("k"),
        Uniqueness("k"),
        Distinctness("k"),
        Entropy("k"),
        Histogram("k", max_detail_bins=TOP_K),
    ]
    with config.configure(device_spill_grouping=False):
        ctx = AnalysisRunner.do_analysis_run(whole, analyzers)
    filtered = CountDistinct("k", where="k % 2 = 0")
    with config.configure(device_spill_grouping=False):
        ctx_w = AnalysisRunner.do_analysis_run(whole, [filtered])
    want_w = ctx_w.metric(filtered).value.get()
    assert abs(got["CountDistinct_where"] - want_w) <= 1e-9 * max(
        1.0, abs(want_w)
    ), (got["CountDistinct_where"], want_w)
    print(
        f"{'CountDistinct/where':>14}: multihost "
        f"{got['CountDistinct_where']:.9g} == arrow {want_w:.9g}"
    )
    for a in analyzers[:4]:
        want = ctx.metric(a).value.get()
        have = got[a.name]
        assert abs(have - want) <= 1e-9 * max(1.0, abs(want)), (
            a.name, have, want,
        )
        print(f"{a.name:>14}: multihost {have:.9g} == arrow {want:.9g}")
    dist = ctx.metric(analyzers[4]).value.get()
    want_hist = {str(k): v.absolute for k, v in dist.values.items()}
    assert got["histogram_bins"] == dist.number_of_bins
    # tie-breaking at the k-th bin may pick different equal-count
    # keys; counts multiset and all common keys must agree exactly
    assert sorted(got["histogram"].values()) == sorted(
        want_hist.values()
    ), (got["histogram"], want_hist)
    for k in set(got["histogram"]) & set(want_hist):
        assert got["histogram"][k] == want_hist[k], k
    print(f"{'Histogram':>14}: multihost top-{TOP_K} == arrow")

    # f64 ground truth: whole table, host path
    f64_whole = Dataset.from_arrow(f64_table)
    f64_analyzers = [
        CountDistinct("k"), Uniqueness("k"), Distinctness("k"),
    ]
    with config.configure(device_spill_grouping=False):
        ctx_f = AnalysisRunner.do_analysis_run(f64_whole, f64_analyzers)
    for a in f64_analyzers:
        want = ctx_f.metric(a).value.get()
        have = got_f64[a.name]
        assert abs(have - want) <= 1e-9 * max(1.0, abs(want)), (
            a.name, have, want,
        )
        print(
            f"{a.name + '/f64':>14}: multihost {have:.9g} "
            f"== arrow {want:.9g}"
        )
    print(
        "f64 host-packed-bits shuffle (2 processes): "
        "f64 metrics == whole-table Arrow"
    )

    # overflow ground truth: the constant key, full count
    assert got_overflow == {"key": 7, "total": N_OVERFLOW_ROWS}, (
        got_overflow
    )
    print(
        "constant-key bucket overflow (2 processes): "
        "spill overflow -> host fallback == whole-table"
    )
    print(
        "multi-host grouping (2 processes, loopback, device shuffle): "
        "metrics == whole-table Arrow"
    )


if __name__ == "__main__":
    main()
