"""Row-level verification results: per-row pass/fail per constraint.

Reference: newer-upstream row-level results (SURVEY.md §2.2
"FilteredRowOutcome", ``VerificationResult.rowLevelResultsAsDataFrame``):
row-level-capable analyzers also emit a per-row boolean outcome column.

Supported families:

- **mask/predicate**: Completeness, Compliance (and every Check method
  that compiles to it: is_contained_in, is_non_negative, satisfies,
  ...), PatternMatch (and contains_email/url/...);
- **grouping**: Uniqueness and UniqueValueRatio (a row passes iff
  its key occurs once — the reference's RowLevelGroupedConstraint
  rule for both);
- **asserted-value** (r4, reference's RowLevelAssertedConstraint):
  MinLength/MaxLength (per-row string length) and Minimum/Maximum
  (per-row numeric value) apply the CONSTRAINT'S OWN assertion to each
  row's value — e.g. ``has_min_length("s", lambda v: v >= 3)`` marks
  exactly the too-short rows. Null rows pass (the reference's default
  NullBehavior.Ignore; Completeness is the analyzer that flags nulls).

Filtered-row semantics are configurable (reference:
``AnalyzerOptions.filteredRow``): rows excluded by a ``where`` filter
count as PASSING under the default ``filtered_row_outcome="true"``, or
come back as SQL NULL under ``"null"`` (the outcome column is then a
nullable boolean, matching the reference's NULLED FilteredRowOutcome).

Outcomes are computed vectorized — device ops for predicate/mask work,
one host ``np.unique`` pass for uniqueness, assertions evaluated once
per UNIQUE value then gathered — never per-row Python.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
import pyarrow as pa

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.basic import (
    Completeness,
    Compliance,
    Maximum,
    MaxLength,
    Minimum,
    MinLength,
    PatternMatch,
)
from deequ_tpu.analyzers.grouping import Uniqueness, UniqueValueRatio
from deequ_tpu.data.table import ColumnRequest, Dataset, Kind, ROW_MASK
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    ConstraintDecorator,
)
from deequ_tpu.sql.predicate import compile_predicate


class _OracleCache:
    """Per-call materialization cache: one export touches only the
    columns its row-level constraints actually request, each at most
    ONCE — the row mask is built a single time, a ``where`` predicate
    shared by several constraints compiles and evaluates once, and a
    column two constraints both read is pulled from the source once
    (parquet sources re-read on every ``materialize``). Scoped to one
    ``row_level_results`` / egress-finalize call so nothing outlives
    the export."""

    def __init__(self, data: Dataset):
        self._data = data
        self._arrays: Dict[str, np.ndarray] = {}
        self._row_mask: Optional[np.ndarray] = None
        self._where: Dict[str, Optional[np.ndarray]] = {}

    def materialize(self, req: ColumnRequest) -> np.ndarray:
        if req.key not in self._arrays:
            self._arrays[req.key] = self._data.materialize(req)
        return self._arrays[req.key]

    def row_mask(self) -> np.ndarray:
        if self._row_mask is None:
            self._row_mask = np.ones(self._data.num_rows, dtype=bool)
        return self._row_mask


def _full_batch(
    data: Dataset, requests, cache: Optional[_OracleCache] = None
) -> Dict[str, np.ndarray]:
    mat = cache.materialize if cache is not None else data.materialize
    batch = {r.key: mat(r) for r in requests}
    for r in requests:
        mask_key = f"{r.column}::mask"
        if mask_key not in batch:
            batch[mask_key] = mat(ColumnRequest(r.column, "mask"))
    batch[ROW_MASK] = (
        cache.row_mask()
        if cache is not None
        else np.ones(data.num_rows, dtype=bool)
    )
    return batch


def _where_pass(
    where: Optional[str],
    data: Dataset,
    cache: Optional[_OracleCache] = None,
) -> Optional[np.ndarray]:
    """True for rows EXCLUDED by the filter (they pass by default)."""
    if where is None:
        return None
    if cache is not None and where in cache._where:
        return cache._where[where]
    pred = compile_predicate(where, data)
    batch = _full_batch(data, pred.requests, cache)
    out = ~np.asarray(jax.device_get(pred.complies(batch)), dtype=bool)
    if cache is not None:
        cache._where[where] = out
    return out


def _asserted_per_value(
    values: np.ndarray, valid: np.ndarray, assertion
) -> Optional[np.ndarray]:
    """assertion(value) per row, evaluated once per UNIQUE value and
    gathered back (the assertion is a Python scalar callable; a direct
    per-row loop would crawl on wide data). Invalid (null) rows pass —
    NullBehavior.Ignore, the reference's default — and their
    zero-fill placeholders NEVER reach the assertion (a partial
    assertion like ``1/v > 0`` must not see values outside the
    non-null domain). An assertion that still raises degrades to "no
    row-level column" (None) rather than aborting the whole export —
    the aggregate path already reported the same exception as a
    FAILURE ConstraintResult."""
    out = np.ones(len(values), dtype=bool)
    real = values[valid]
    uniques, inverse = np.unique(real, return_inverse=True)
    try:
        lut = np.fromiter(
            (bool(assertion(v)) for v in uniques.tolist()),
            dtype=bool,
            count=len(uniques),
        )
    except Exception:  # noqa: BLE001 — degrade, mirroring the
        return None  # aggregate constraint's FAILURE result
    out[valid] = lut[inverse]
    return out


def _outcome_for(
    analyzer: Analyzer,
    data: Dataset,
    assertion=None,
    excluded: Optional[np.ndarray] = None,
    cache: Optional[_OracleCache] = None,
) -> Optional[np.ndarray]:
    mat = cache.materialize if cache is not None else data.materialize

    def _asserted(repr_name: str) -> Optional[np.ndarray]:
        values = np.asarray(
            mat(ColumnRequest(analyzer.column, repr_name))
        )
        valid = np.asarray(
            mat(ColumnRequest(analyzer.column, "mask")),
            dtype=bool,
        )
        if excluded is not None:
            # where-excluded rows are outside the assertion's domain
            # exactly like nulls: a partial assertion safe on the
            # FILTERED data must not see their values (the caller
            # overrides their outcome per filtered_row_outcome)
            valid = valid & ~excluded
        return _asserted_per_value(values, valid, assertion)

    if isinstance(analyzer, (MinLength, MaxLength)):
        if assertion is None:
            return None
        out = _asserted("lengths")
    elif isinstance(analyzer, (Minimum, Maximum)):
        if assertion is None:
            return None
        out = _asserted("values")
    elif isinstance(analyzer, Completeness):
        mask = mat(ColumnRequest(analyzer.column, "mask"))
        out = np.asarray(mask, dtype=bool).copy()
    elif isinstance(analyzer, Compliance):
        pred = compile_predicate(analyzer.predicate, data)
        batch = _full_batch(data, pred.requests, cache)
        out = np.asarray(
            jax.device_get(pred.complies(batch)), dtype=bool
        ).copy()
    elif isinstance(analyzer, PatternMatch):
        import re

        codes = mat(ColumnRequest(analyzer.column, "codes"))
        mask = mat(ColumnRequest(analyzer.column, "mask"))
        dictionary = data.dictionary(analyzer.column)
        prog = re.compile(analyzer.pattern)
        lut = np.zeros(max(len(dictionary), 1) + 1, dtype=bool)
        for i, value in enumerate(dictionary):
            if value is not None and prog.search(str(value)):
                lut[i] = True
        idx = np.where(codes < 0, len(lut) - 1, codes)
        out = lut[np.clip(idx, 0, len(lut) - 1)] & np.asarray(
            mask, dtype=bool
        )
    elif isinstance(analyzer, (Uniqueness, UniqueValueRatio)):
        columns = analyzer.grouping_columns()
        # fold columns into one exact group id via successive np.unique
        # in each column's NATIVE dtype — no float64 cast (int64 ids
        # above 2^53 must stay distinct, exactly like the HLL hashing)
        group_ids: Optional[np.ndarray] = None
        for c in columns:
            kind = data.schema.kind_of(c)
            repr_name = "codes" if kind == Kind.STRING else "values"
            values = np.asarray(mat(ColumnRequest(c, repr_name)))
            mask = np.asarray(mat(ColumnRequest(c, "mask")), dtype=bool)
            _, col_ids = np.unique(values, return_inverse=True)
            # validity joins the key so NULL is its own value,
            # distinct from the zero-fill
            col_ids = col_ids * 2 + mask.astype(np.int64)
            if group_ids is None:
                group_ids = col_ids
            else:
                pair = np.stack([group_ids, col_ids], axis=1)
                _, group_ids = np.unique(
                    pair, axis=0, return_inverse=True
                )
        _, inverse = np.unique(group_ids, return_inverse=True)
        if inverse.size == 0:
            return np.zeros(0, dtype=bool)
        if excluded is not None:
            # occurrence counts over the FILTERED data only: a key
            # unique within the filter passes even if where-excluded
            # rows share it (their own outcome is overridden by
            # filtered_row_outcome) — review finding r5
            counts = np.bincount(
                inverse[~excluded], minlength=inverse.max() + 1
            )
        else:
            counts = np.bincount(inverse)
        out = counts[inverse] == 1
    else:
        return None
    return out


def row_level_results(
    check_results,
    data: Dataset,
    filtered_row_outcome: str = "true",
) -> Dataset:
    """One boolean column per row-level-capable constraint, named by the
    constraint, over ``data`` (the dataset the suite ran on).

    ``filtered_row_outcome`` — what a row EXCLUDED by the constraint's
    ``where`` filter reports (reference: AnalyzerOptions.filteredRow):
    ``"true"`` (default) marks it passing; ``"null"`` yields SQL NULL
    in a nullable boolean column."""
    if filtered_row_outcome not in ("true", "null"):
        raise ValueError(
            "filtered_row_outcome must be 'true' or 'null', got "
            f"{filtered_row_outcome!r}"
        )
    columns: Dict[str, pa.Array] = {}
    # one shared materialization cache for the whole export: only the
    # columns the row-level constraints touch, each loaded once
    cache = _OracleCache(data)
    for check, result in check_results.items():
        for cr in result.constraint_results:
            constraint = cr.constraint
            if isinstance(constraint, ConstraintDecorator):
                inner = constraint.inner
            else:
                inner = constraint
            if not isinstance(inner, AnalysisBasedConstraint):
                continue
            try:
                excluded = _where_pass(
                    getattr(inner.analyzer, "where", None), data,
                    cache,
                )
                outcome = _outcome_for(
                    inner.analyzer,
                    data,
                    assertion=inner.assertion,
                    excluded=excluded,
                    cache=cache,
                )
            except Exception:  # noqa: BLE001 — degrade: an unplannable
                # predicate (compile_predicate in _where_pass or the
                # Compliance branch) drops THIS constraint's column
                # only, mirroring _asserted_per_value's discipline; the
                # aggregate path already reported the same exception as
                # a FAILURE ConstraintResult
                continue
            if outcome is None:
                continue
            if excluded is None:
                columns[str(constraint)] = pa.array(outcome)
            elif filtered_row_outcome == "true":
                columns[str(constraint)] = pa.array(outcome | excluded)
            else:  # "null": excluded rows are SQL NULL
                columns[str(constraint)] = pa.array(
                    outcome, mask=excluded
                )
    if not columns:
        return Dataset(pa.table({"__no_row_level_constraints__": pa.array([], pa.bool_())}))
    return Dataset(pa.table(columns))
