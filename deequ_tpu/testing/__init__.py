"""Deterministic test doubles for the resilience machinery
(docs/RESILIENCE.md). Not imported by library code — tests only."""

from deequ_tpu.testing.faults import FaultInjectingDataset

__all__ = ["FaultInjectingDataset"]
