"""Columnar dataset: Arrow ingest and device-batch materialization.

This is deequ_tpu's L0/L1 replacement for Spark DataFrames (SURVEY.md §1,
§7 stage 0). A :class:`Dataset` wraps a ``pyarrow.Table`` and materializes
*device representations* of columns on demand:

- ``values``   — numeric payload (nulls zero-filled; see mask)
- ``mask``     — validity bitmap as bool (True = non-null), AND row mask
- ``codes``    — dictionary codes (int32) for string/categorical columns,
                 with the dictionary kept host-side (strings never reach
                 the TPU — SURVEY.md §7 hard part #3)
- ``lengths``  — utf8 lengths for string columns (MinLength/MaxLength)

Batches are fixed-size and zero-padded (padding rows carry
``__row_mask__ == False``) so that every batch has the same static shape
and the fused analyzer scan compiles exactly once.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

ROW_MASK = "__row_mask__"


class Kind(enum.Enum):
    """Logical column kinds (maps Arrow types to analyzer preconditions)."""

    INTEGRAL = "Integral"
    FRACTIONAL = "Fractional"
    BOOLEAN = "Boolean"
    STRING = "String"
    TIMESTAMP = "Timestamp"
    UNKNOWN = "Unknown"

    @property
    def is_numeric(self) -> bool:
        return self in (Kind.INTEGRAL, Kind.FRACTIONAL, Kind.BOOLEAN)


def _kind_of(arrow_type: pa.DataType) -> Kind:
    if pa.types.is_boolean(arrow_type):
        return Kind.BOOLEAN
    if pa.types.is_integer(arrow_type):
        return Kind.INTEGRAL
    if pa.types.is_floating(arrow_type) or pa.types.is_decimal(arrow_type):
        return Kind.FRACTIONAL
    if pa.types.is_string(arrow_type) or pa.types.is_large_string(arrow_type):
        return Kind.STRING
    if pa.types.is_dictionary(arrow_type):
        return _kind_of(arrow_type.value_type)
    if pa.types.is_timestamp(arrow_type) or pa.types.is_date(arrow_type):
        return Kind.TIMESTAMP
    return Kind.UNKNOWN


@dataclass(frozen=True)
class Field:
    name: str
    kind: Kind


@dataclass(frozen=True)
class Schema:
    fields: Tuple[Field, ...]

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def has_column(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def kind_of(self, name: str) -> Kind:
        for f in self.fields:
            if f.name == name:
                return f.kind
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)


@dataclass(frozen=True)
class ColumnRequest:
    """A device representation request: (column, repr)."""

    column: str
    repr: str  # "values" | "mask" | "codes" | "lengths"

    @property
    def key(self) -> str:
        return f"{self.column}::{self.repr}"


class Dataset:
    """In-memory columnar dataset over a ``pyarrow.Table``.

    Construction helpers accept Arrow tables, pandas DataFrames, or plain
    dicts of Python/numpy sequences. All device materializations are cached
    per (column, repr) as contiguous numpy arrays; batches are views plus a
    single zero-pad for the tail.
    """

    def __init__(self, table: pa.Table):
        self._table = table.combine_chunks()
        self._schema = Schema(
            tuple(
                Field(name, _kind_of(typ))
                for name, typ in zip(table.schema.names, table.schema.types)
            )
        )
        self._materialized: Dict[str, np.ndarray] = {}
        self._dictionaries: Dict[str, np.ndarray] = {}

    # -- construction ---------------------------------------------------

    @staticmethod
    def from_arrow(table: pa.Table) -> "Dataset":
        return Dataset(table)

    @staticmethod
    def from_pandas(df) -> "Dataset":
        return Dataset(pa.Table.from_pandas(df, preserve_index=False))

    @staticmethod
    def from_pydict(data: Dict[str, Sequence]) -> "Dataset":
        return Dataset(pa.table(data))

    # -- metadata -------------------------------------------------------

    @property
    def table(self) -> pa.Table:
        return self._table

    @property
    def num_rows(self) -> int:
        return self._table.num_rows

    @property
    def num_columns(self) -> int:
        return self._table.num_columns

    @property
    def schema(self) -> Schema:
        return self._schema

    def filter_rows(self, mask: np.ndarray) -> "Dataset":
        """Row subset (host-side); used by train/test splits and schema
        validation, not by the metric engine."""
        return Dataset(self._table.filter(pa.array(mask)))

    def select(self, columns: Sequence[str]) -> "Dataset":
        return Dataset(self._table.select(list(columns)))

    # -- dictionaries ---------------------------------------------------

    def dictionary(self, column: str) -> np.ndarray:
        """Host-side dictionary (unique values) for a column; codes index
        into this. Built once per column via Arrow's C++ kernels."""
        if column not in self._dictionaries:
            self._materialize_codes(column)
        return self._dictionaries[column]

    def _materialize_codes(self, column: str) -> None:
        arr = self._table.column(column)
        if pa.types.is_dictionary(arr.type):
            dict_arr = arr.combine_chunks()
        else:
            dict_arr = pc.dictionary_encode(arr).combine_chunks()
        if isinstance(dict_arr, pa.ChunkedArray):
            dict_arr = dict_arr.combine_chunks()
        indices = dict_arr.indices
        codes = (
            pc.fill_null(indices, pa.scalar(-1, indices.type))
            .to_numpy(zero_copy_only=False)
            .astype(np.int32)
        )
        self._materialized[f"{column}::codes"] = np.ascontiguousarray(codes)
        dictionary = dict_arr.dictionary
        self._dictionaries[column] = np.asarray(
            dictionary.to_pylist(), dtype=object
        )

    # -- device materialization ----------------------------------------

    def materialize(self, req: ColumnRequest) -> np.ndarray:
        key = req.key
        if key in self._materialized:
            return self._materialized[key]
        col = self._table.column(req.column)
        kind = self._schema.kind_of(req.column)
        if req.repr == "mask":
            if col.null_count == 0:
                out = np.ones(len(col), dtype=bool)
            else:
                out = ~col.is_null().combine_chunks().to_numpy(
                    zero_copy_only=False
                )
            out = np.ascontiguousarray(out.astype(bool))
        elif req.repr == "values":
            if kind == Kind.STRING:
                raise TypeError(
                    f"column '{req.column}' is a string column; request "
                    "'codes' or 'lengths' instead of 'values'"
                )
            filled = col
            if kind == Kind.TIMESTAMP:
                filled = pc.cast(col, pa.int64())
                if col.null_count:
                    filled = pc.fill_null(filled, pa.scalar(0, pa.int64()))
            elif col.null_count:
                zero = pa.scalar(False) if kind == Kind.BOOLEAN else pa.scalar(
                    0, type=col.type
                )
                filled = pc.fill_null(col, zero)
            out = filled.combine_chunks().to_numpy(zero_copy_only=False)
            if kind == Kind.BOOLEAN:
                out = out.astype(np.int32)
            elif out.dtype == np.float16:
                out = out.astype(np.float32)
            elif out.dtype.kind not in "iuf":
                out = out.astype(np.float64)
            out = np.ascontiguousarray(out)
        elif req.repr == "codes":
            self._materialize_codes(req.column)
            return self._materialized[key]
        elif req.repr == "lengths":
            lengths = pc.fill_null(
                pc.utf8_length(col), pa.scalar(0, pa.int32())
            )
            out = np.ascontiguousarray(
                lengths.combine_chunks()
                .to_numpy(zero_copy_only=False)
                .astype(np.int32)
            )
        else:
            raise ValueError(f"unknown column repr: {req.repr!r}")
        self._materialized[key] = out
        return out

    # -- batching -------------------------------------------------------

    def device_batches(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: Optional[int] = None,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Yield fixed-size batches (host numpy; the engine device_puts).

        Every batch has identical shapes: the tail batch is zero-padded
        and padding rows have ``__row_mask__ == False``; per-column masks
        are pre-ANDed with the row mask so updates need a single mask.
        """
        n = self.num_rows
        if batch_size is None:
            batch_size = n if n > 0 else 1
        batch_size = max(1, batch_size)
        # dedup requests; always provide masks for requested columns
        keys: Dict[str, ColumnRequest] = {}
        for r in requests:
            keys.setdefault(r.key, r)
            mask_req = ColumnRequest(r.column, "mask")
            keys.setdefault(mask_req.key, mask_req)
        full: Dict[str, np.ndarray] = {
            k: self.materialize(r) for k, r in keys.items()
        }
        if n == 0:
            batch = {
                k: np.zeros((batch_size,), dtype=v.dtype)
                for k, v in full.items()
            }
            batch[ROW_MASK] = np.zeros((batch_size,), dtype=bool)
            yield batch
            return
        for start in range(0, n, batch_size):
            stop = min(start + batch_size, n)
            width = stop - start
            pad = batch_size - width
            batch = {}
            for k, v in full.items():
                sl = v[start:stop]
                if pad:
                    sl = np.concatenate(
                        [sl, np.zeros((pad,), dtype=v.dtype)]
                    )
                batch[k] = sl
            row_mask = np.ones((batch_size,), dtype=bool)
            if pad:
                row_mask[width:] = False
            batch[ROW_MASK] = row_mask
            if pad:
                for k in list(batch.keys()):
                    if k.endswith("::mask"):
                        batch[k] = batch[k] & row_mask
            yield batch

    def num_batches(self, batch_size: Optional[int] = None) -> int:
        n = self.num_rows
        if n == 0:
            return 1
        if batch_size is None:
            return 1
        return -(-n // batch_size)
