"""Multi-host execution evidence (SURVEY §7 stage 8): two REAL processes
initialize jax.distributed over loopback, profile their own parquet
shards, persist states, and the merged states equal the whole-table run.
Delegates to examples/multihost_profiling.py — the runnable demo IS the
test."""

import os
import subprocess
import sys

import pytest


def test_two_process_loopback_merge_equals_whole_table():
    """Spawns real worker processes; ~60-90s wall (backend init x2)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", "multihost_profiling.py")
    result = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=400,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "merged == whole-table" in result.stdout


@pytest.mark.xfail(
    os.environ.get("JAX_PLATFORMS", "").startswith("cpu"),
    reason=(
        "CPU-backend multiprocess limitation: the two-process "
        "all_to_all device shuffle needs a real cross-host collective "
        "backend; under JAX_PLATFORMS=cpu the coordinated mesh path "
        "is exercised only up to backend init (tracked in ROADMAP "
        "item 5 — runs for real on a multi-host TPU slice)"
    ),
    strict=False,
)
def test_cross_host_grouping_shuffle_equals_whole_table():
    """The cross-host high-cardinality grouping path (VERDICT r4 next
    #3): two real processes, one global mesh, 10M rows with ~9.7M
    distinct keys split 60/40 — CountDistinct/Uniqueness/Distinctness/
    Entropy/Histogram through the bucketed all_to_all device shuffle
    (NO Arrow fallback) must equal the whole-table host run. The SAME
    coordinator pair (one jax.distributed init) then runs two more
    scenarios: f64 keys through the host-packed canonical-bit path
    (what a TPU backend takes — forced on CPU via the test hook), and
    a constant-key column that overflows a hash bucket, where
    SpillOverflow must raise UNIFORMLY on both hosts (no one-sided
    hang) and the host Arrow fallback still yields exact counts.
    Delegates to examples/multihost_grouping.py — the runnable demo IS
    the test."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", "multihost_grouping.py")
    result = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "metrics == whole-table Arrow" in result.stdout
    assert "f64 metrics == whole-table Arrow" in result.stdout
    assert (
        "spill overflow -> host fallback == whole-table" in result.stdout
    )


@pytest.mark.xfail(
    os.environ.get("JAX_PLATFORMS", "").startswith("cpu"),
    reason=(
        "CPU-backend multiprocess limitation: the fleet's collective "
        "scans fail per-batch with 'Multiprocess computations aren't "
        "implemented on the CPU backend' and the resilience layer "
        "quarantines every batch UNIFORMLY on both hosts (no one-sided "
        "hang) — the elastic placement, replicated run queue, and "
        "process-sharded feed all execute; only the collective itself "
        "cannot (tracked in ROADMAP item 5 — runs for real on a "
        "multi-host TPU slice)"
    ),
    strict=False,
)
def test_distributed_service_sharded_feed_equals_whole_table():
    """The 2-process distributed SERVICE (this PR's tentpole second
    half): each process runs an identical single-worker service
    replica (multi-controller SPMD — process 0's queue IS the fleet's
    run queue), every run leases the full 8-device global mesh from
    the elastic placer, and the process-sharded ingest feeds each
    host's own parquet row-group shard into shared global arrays. The
    fleet's metrics must equal a single-process whole-table run.
    Delegates to examples/distributed_service.py — the runnable demo
    IS the test."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", "distributed_service.py")
    result = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=700,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "fleet metrics == whole-table" in result.stdout
