"""Predicate DSL unit tests, incl. SQL three-valued-logic regressions."""

import pyarrow as pa
import pytest

from deequ_tpu.analyzers import Compliance, Maximum, Mean
from deequ_tpu.data import Dataset
from deequ_tpu.sql import PredicateParseError, parse_predicate


def compliance(ds, predicate):
    metric = Compliance("t", predicate).calculate(ds)
    assert metric.value.is_success, metric.value
    return metric.value.get()


@pytest.fixture
def numeric_ds():
    return Dataset.from_pydict({"x": [0, 1, 2, 3], "y": [3, 2, 1, 0]})


class TestPredicates:
    def test_comparisons(self, numeric_ds):
        assert compliance(numeric_ds, "x >= 2") == 0.5
        assert compliance(numeric_ds, "x < y") == 0.5
        assert compliance(numeric_ds, "x + y = 3") == 1.0
        assert compliance(numeric_ds, "x * 2 > y") == 0.5

    def test_boolean_logic(self, numeric_ds):
        assert compliance(numeric_ds, "x > 0 AND y > 0") == 0.5
        assert compliance(numeric_ds, "x = 0 OR y = 0") == 0.5
        assert compliance(numeric_ds, "NOT (x = 0)") == 0.75

    def test_between(self, numeric_ds):
        assert compliance(numeric_ds, "x BETWEEN 1 AND 2") == 0.5

    def test_in_list_numeric(self, numeric_ds):
        assert compliance(numeric_ds, "x IN (0, 3)") == 0.5
        assert compliance(numeric_ds, "x NOT IN (0, 3)") == 0.5

    def test_in_list_with_null_literal(self, numeric_ds):
        # SQL 3VL: x IN (1, NULL) is TRUE only on a match, else NULL
        assert compliance(numeric_ds, "x IN (1, NULL)") == 0.25
        assert compliance(numeric_ds, "x IN (NULL)") == 0.0
        # x NOT IN (1, NULL): never TRUE (non-matches are NULL)
        assert compliance(numeric_ds, "x NOT IN (1, NULL)") == 0.0

    def test_null_comparisons_not_compliant(self):
        ds = Dataset.from_arrow(
            pa.table({"x": pa.array([1.0, None, 3.0], pa.float64())})
        )
        assert compliance(ds, "x > 0") == pytest.approx(2 / 3)
        assert compliance(ds, "x IS NULL") == pytest.approx(1 / 3)
        assert compliance(ds, "x IS NOT NULL") == pytest.approx(2 / 3)

    def test_division_by_zero_is_null(self, numeric_ds):
        # y = 0 in the last row -> x / y is NULL there
        assert compliance(numeric_ds, "x / y >= 0") == 0.75

    def test_string_like(self):
        ds = Dataset.from_pydict({"s": ["apple", "banana", "cherry", None]})
        assert compliance(ds, "s LIKE 'b%'") == 0.25
        assert compliance(ds, "s RLIKE 'an'") == 0.25
        assert compliance(ds, "s NOT LIKE 'b%'") == 0.5  # null not compliant

    def test_length_function(self):
        ds = Dataset.from_pydict({"s": ["a", "bb", "ccc", None]})
        assert compliance(ds, "LENGTH(s) >= 2") == 0.5

    def test_parse_errors(self):
        with pytest.raises(PredicateParseError):
            parse_predicate("x >>> 1")
        with pytest.raises(PredicateParseError):
            parse_predicate("AND x")

    def test_string_column_to_column_comparison(self):
        """Two string columns compare by VALUE, not by dictionary code
        (codes come from unrelated dictionaries in order of appearance)."""
        ds = Dataset.from_pydict(
            {"a": ["x", "y", "z", "w"], "b": ["x", "q", "z", "x"]}
        )
        assert compliance(ds, "a = b") == 0.5
        assert compliance(ds, "a != b") == 0.5
        # lexicographic: x<x F, y<q F, z<z F, w<x T
        assert compliance(ds, "a < b") == 0.25
        assert compliance(ds, "a >= b") == 0.75

    def test_string_column_literal_ordering(self):
        ds = Dataset.from_pydict({"s": ["apple", "pear", "zebra", None]})
        assert compliance(ds, "s >= 'pear'") == 0.5
        assert compliance(ds, "'pear' <= s") == 0.5
        assert compliance(ds, "s < 'b'") == 0.25

    def test_string_numeric_mix_rejected(self):
        """Comparing a string column to a numeric operand (or doing
        arithmetic on codes) degrades to a failure METRIC — never a
        silent wrong answer, never a raised exception."""
        ds = Dataset.from_pydict({"s": ["a", "b"], "x": [1.0, 2.0]})
        for pred in ("s = 1", "s < x", "s + 1 > 0"):
            metric = Compliance("t", pred).calculate(ds)
            assert metric.value.is_failure, pred


class TestNullableBoolean:
    def test_numeric_analyzers_on_nullable_bool(self):
        ds = Dataset.from_arrow(
            pa.table({"b": pa.array([True, None, False, True])})
        )
        mean = Mean("b").calculate(ds)
        assert mean.value.is_success, mean.value
        assert mean.value.get() == pytest.approx(2 / 3)
        assert Maximum("b").calculate(ds).value.get() == 1.0
