"""Test env: force JAX onto CPU with 8 virtual devices, so every
'distributed' behavior is tested on a fake mesh with no real cluster —
the TPU transfer of the reference's local-Spark fixture (SURVEY.md §4:
SparkContextSpec -> virtual-device mesh).

NOTE: this image pre-imports jax (sitecustomize on PYTHONPATH) with
JAX_PLATFORMS=axon, so the env var is already consumed; the supported
override point is jax.config BEFORE any backend is initialized."""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# Hermetic compile cache: loading a persistent-cache executable written
# earlier in the same session aborts the whole process (SIGABRT inside
# XLA CPU) in test_differential's mesh test on this jax build —
# reproducibly, even with a freshly-emptied cache directory. Disable
# the cache for tests; the suite recompiles everything and stays well
# inside the timing budget.
os.environ["DEEQU_TPU_COMPILE_CACHE"] = ""

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def cpu_mesh():
    import numpy as np
    from jax.sharding import Mesh

    devices = np.array(jax.devices("cpu")[:8])
    return Mesh(devices, ("dp",))
