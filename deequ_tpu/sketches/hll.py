"""HyperLogLog primitives for the device pass.

Reference: ``analyzers/catalyst/StatefulHyperloglogPlus`` (SURVEY.md
§2.3): HLL++ registers as packed words updated per row inside Tungsten;
merge = word-wise max. TPU design (per SURVEY's table): registers are an
int32[m] device vector; the per-batch update is hash -> leading-zero
count -> scatter-max; the merge is an elementwise max (a ``lax.max``
all-reduce across the mesh / across persisted states).

Hashing is built from 32-bit lanes ONLY — the TPU has no native 64-bit
integer path (XLA's x64 rewriter refuses u64 bitcasts), and 32-bit
murmur-style mixing maps perfectly onto the VPU:

- integral columns split the raw int64 payload into (hi u32, lo u32) —
  exact for the full 64-bit range (IDs, epoch nanos); floating columns
  canonicalize to a (float32, float32 residual) pair, stable across
  f32/f64 storage of equal values;
- the word pair mixes through murmur3's 32-bit finalizer into two
  independent 32-bit hashes: h1 supplies the register index (top
  P bits), h2 supplies the leading-zero rank;
- strings hash host-side ONCE per dictionary entry (blake2b-8, split
  into two u32 words) into device lookup tables gathered by code.
"""

from __future__ import annotations

import hashlib
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

P = 14  # precision: m = 2^14 registers => ~0.8% relative error
M = 1 << P

_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)


def fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """murmur3 32-bit finalizer (avalanche); h: uint32 array."""
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def hash_pair_numeric(
    values: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Produce two independent u32 hashes per value, dispatching on the
    column dtype:

    - **integral/boolean** columns hash the RAW 64-bit payload as two
      u32 words (hi/lo via shifts) — exact for the full int64 range.
      Float canonicalization here would collide catastrophically above
      2^53 (snowflake IDs, epoch nanos): the reference's HLL++ hashes
      the raw long, so must we.
    - **floating** columns canonicalize to (float32 hi, float32
      residual) — exact for floats and stable across f32/f64 storage of
      equal values.
    """
    if jnp.issubdtype(values.dtype, jnp.floating):
        # -0.0 -> +0.0 via where, NOT `+ 0.0`: XLA's algebraic
        # simplifier elides add(x, 0) inside larger graphs (observed
        # inside lax.cond branches, r5), which would make the hash of
        # -0.0 depend on compilation context
        as_f64 = values.astype(jnp.float64)
        as_f64 = jnp.where(as_f64 == 0.0, 0.0, as_f64)
        hi = as_f64.astype(jnp.float32)
        lo = (as_f64 - hi.astype(jnp.float64)).astype(jnp.float32)
        lo = jnp.where(lo == 0.0, jnp.float32(0.0), lo)
        hi_bits = jax.lax.bitcast_convert_type(hi, jnp.uint32)
        lo_bits = jax.lax.bitcast_convert_type(lo, jnp.uint32)
    else:
        as_i64 = values.astype(jnp.int64)
        lo_bits = (as_i64 & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        hi_bits = (
            (as_i64 >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)
        ).astype(jnp.uint32)
    h1 = fmix32(lo_bits ^ fmix32(hi_bits ^ _GOLDEN))
    h2 = fmix32(hi_bits ^ fmix32(lo_bits ^ _C1))
    return h1, h2


def dictionary_hash_pairs(
    dictionary: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Stable (u32, u32) hash per dictionary entry (host-side, once)."""
    n = max(len(dictionary), 1)
    h1 = np.zeros(n, dtype=np.uint32)
    h2 = np.zeros(n, dtype=np.uint32)
    for i, value in enumerate(dictionary):
        if value is None:
            continue
        digest = hashlib.blake2b(
            str(value).encode("utf-8"), digest_size=8
        ).digest()
        words = np.frombuffer(digest, dtype=np.uint32)
        h1[i], h2[i] = words[0], words[1]
    return h1, h2


def _index_and_rank(h1, h2, mask):
    """THE one (register index, rho rank) derivation — the single and
    column-stacked update paths must share it: divergence here would put
    equal values in different registers, and a max-merge of states from
    the two paths would then double-count (the v1/v2 hazard documented
    in analyzers/states.py STATE_FORMAT_VERSIONS)."""
    idx = (h1 >> np.uint32(32 - P)).astype(jnp.int32)
    rho = jnp.minimum(jax.lax.clz(h2) + 1, 33).astype(jnp.int32)
    return jnp.where(mask, idx, 0), jnp.where(mask, rho, 0)


REGISTER_DTYPE = jnp.int8  # rho <= 33 fits i8: 4x fewer wire bytes than
# i32 when states cross the tunnel (the scatter itself runs in i32 —
# narrow scatters lower poorly — and the result narrows after)


def registers_from_hash_pair(
    h1: jnp.ndarray, h2: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """One batch of hash pairs -> int8[M] register vector (scatter-max).

    rho comes from h2's leading zeros (1..33) — supporting max register
    rank 33, ample for cardinalities far beyond 2^40."""
    idx, rho = _index_and_rank(h1, h2, mask)
    from deequ_tpu.sketches import pallas_scatter

    pallas = pallas_scatter.scatter_max(idx[None, :], rho[None, :], M)
    if pallas is not None:
        return pallas[0].astype(REGISTER_DTYPE)
    return (
        jnp.zeros(M, dtype=jnp.int32)
        .at[idx]
        .max(rho)
        .astype(REGISTER_DTYPE)
    )


def registers_from_hash_pair_stacked(
    h1: jnp.ndarray, h2: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Column-stacked variant: (C, B) hash pairs -> (C, M) registers via
    ONE scatter-max into a flat (C*M,) vector (per-column register
    blocks indexed by col*M + idx). Behind ``config.pallas_scatter``
    the unroll-16 SMEM kernel takes over with a (C, G) grid (a flat
    C*M register file exceeds SMEM) — bit-identical either way."""
    idx, rho = _index_and_rank(h1, h2, mask)
    from deequ_tpu.sketches import pallas_scatter

    pallas = pallas_scatter.scatter_max(idx, rho, M)
    if pallas is not None:
        return pallas.astype(REGISTER_DTYPE)
    n_cols = idx.shape[0]
    col_ids = jax.lax.broadcasted_iota(jnp.int32, idx.shape, 0)
    flat = (col_ids * M + idx).ravel()
    return (
        jnp.zeros(n_cols * M, dtype=jnp.int32)
        .at[flat]
        .max(rho.ravel())
        .reshape(n_cols, M)
        .astype(REGISTER_DTYPE)
    )


# dict sizes up to this use the presence path (measured on v5e: the
# compare-reduce beats the per-row gather+scatter at every D tested up
# to 4096 — 261ms -> ~0ms at D=64, 261ms -> 57ms at D=4096 for a
# (4, 2^21) block; crossover extrapolates to D ~ 16k. docs/PERF.md.)
PRESENCE_DICT_CAP = 4096

# D-axis tile for the presence compare-reduce (bounds the (C, TILE, B)
# intermediate if a backend fails to fuse it; see
# registers_from_code_presence)
_PRESENCE_D_TILE = 256


def registers_from_code_presence(
    codes: jnp.ndarray,  # (C, B) int codes, -1 = null
    mask: jnp.ndarray,  # (C, B) validity (row mask pre-ANDed)
    lut1: jnp.ndarray,  # (C, D) u32 per-dictionary-entry hashes
    lut2: jnp.ndarray,
) -> jnp.ndarray:
    """Registers for dict-encoded columns WITHOUT touching rows with a
    scatter: a register's value is the max rho over the DISTINCT values
    present, so scattering each dictionary entry once, masked by
    whether its code occurs in the batch, yields bit-identical
    registers to scattering every row (max over duplicates ==
    single occurrence). Presence is a (C, D, B)->(C, D) compare-reduce
    the VPU eats at full rate, vs one serialized scatter element per
    ROW (~145M elem/s measured) on the per-row path. Null codes (-1)
    match no dictionary slot and vanish."""
    present = tiled_code_presence(codes, mask, lut1.shape[1], count=False)
    return registers_from_hash_pair_stacked(lut1, lut2, present)


def tiled_code_presence(
    codes: jnp.ndarray,  # (C, B) int codes, -1 = null
    mask: jnp.ndarray,  # (C, B) validity
    D: int,
    count: bool,
) -> jnp.ndarray:
    """(C, D) per-dictionary-slot presence (``count=False``, bool) or
    occurrence counts (``count=True``, i32) via the compare-reduce.

    The D axis is chunked so the (C, TILE, B) intermediate stays
    bounded even on a backend where XLA does NOT fuse the compare into
    the reduce (at the D=4096 cap with B=2^21 an unfused full-D
    intermediate would be tens of GB — r4 advisory). TILE=256 keeps
    the worst case ~2 GB/column-block and measured the same as the
    unchunked form (the reduce dominates either way). Shared by the
    HLL presence path here and DataType's count path
    (analyzers/datatype.py) so the tiling can never diverge."""
    codes_i32 = codes.astype(jnp.int32)
    tile = min(D, _PRESENCE_D_TILE)
    parts = []
    for d0 in range(0, D, tile):
        d = jnp.arange(d0, min(d0 + tile, D), dtype=jnp.int32)
        hits = (codes_i32[:, None, :] == d[None, :, None]) & mask[:, None, :]
        parts.append(
            hits.sum(axis=2, dtype=jnp.int32) if count else hits.any(axis=2)
        )
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)


# ------------------------------------------------------------------
# adaptive sorted-dedup update for numeric columns (r5)
# ------------------------------------------------------------------

# Registers only see DISTINCT values (register = max over duplicates),
# so a column whose per-batch distinct count U fits a static dictionary
# can sort the batch, compact the uniques, and scatter U elements
# instead of B. Measured on v5e (docs/PERF.md r5 table): sort 3.6 ms +
# compaction 2.9 ms vs 15.2 ms for the full per-row scatter at
# B = 2^21 — 2.3x for mid-cardinality columns (TPC-DS quantities,
# cent-denominated prices). High-cardinality columns keep the full
# scatter: the path is gated per GROUP by a linear-counting estimate
# from the CARRIED registers, so batch 1 (empty state) and any
# high-cardinality history never pay the sort.
DEDUP_DICT_CAP = 16384

# zeros > gate  <=>  linear-counting estimate -M*ln(zeros/M) < ~12k
# (margin below DEDUP_DICT_CAP so the inner exact U <= D check rarely
# has to fall back mid-branch)
_DEDUP_ZEROS_GATE = int(M * np.exp(-0.75))


def dedup_gate(registers: jnp.ndarray) -> jnp.ndarray:
    """(..., M) carried registers -> (...,) bool: the state's linear-
    counting estimate says this column is mid-cardinality. All-zero
    registers (first batch / empty column) gate FALSE: with no
    history the full scatter is the safe choice."""
    zeros = jnp.sum(registers == 0, axis=-1)
    return (zeros < M) & (zeros > _DEDUP_ZEROS_GATE)


def _dedup_supported(dtype) -> bool:
    """Sorted dedup needs a total order and a free sentinel: any real
    float or integer dtype qualifies (bool is NOT an integer subtype,
    so two-value boolean columns keep the plain scatter)."""
    return jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(
        dtype, jnp.integer
    )


def dedup_column_registers(
    xc: jnp.ndarray,  # (B,) values
    maskc: jnp.ndarray,  # (B,) validity
) -> jnp.ndarray:
    """(M,) batch registers for ONE column via sort + unique
    compaction. Bit-identical to the per-row scatter: the dictionary
    entries are the batch's own values, hashed by the SAME
    hash_pair_numeric, and max over duplicates == single occurrence.

    Sentinel discipline: masked slots sort as ``sentval`` (+inf for
    floats, iinfo.max for ints), which excludes them from the unique
    run — a REAL sentinel-valued element (or NaN, floats only) is
    re-added as a flagged extra dictionary slot. Exotic NaN payloads
    collapse to the canonical NaN here (the per-row path hashes raw
    payload bits); both orderings count NaN as one value on canonical
    data, and states from the two paths still max-merge safely.

    A column whose ACTUAL U exceeds the cap falls back to its own full
    scatter inside the branch (correctness never depends on the
    caller's gate estimate)."""
    (B,) = xc.shape
    floating = jnp.issubdtype(xc.dtype, jnp.floating)
    D = min(DEDUP_DICT_CAP, B)
    if floating:
        sentval = jnp.asarray(jnp.inf, xc.dtype)
        nan_mask = jnp.isnan(xc)
        keys = jnp.where(maskc & ~nan_mask, xc, sentval)
        sent_flag = jnp.any((xc == sentval) & maskc)
        nan_flag = jnp.any(nan_mask & maskc)
        nan_entry = jnp.asarray(jnp.nan, xc.dtype)
    else:
        sentval = jnp.asarray(jnp.iinfo(xc.dtype).max, xc.dtype)
        keys = jnp.where(maskc, xc, sentval)
        sent_flag = jnp.any((xc == sentval) & maskc)
        nan_flag = jnp.asarray(False)
        nan_entry = sentval  # dead slot (flag stays False)

    s = jnp.sort(keys)
    uniq = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), s[1:] != s[:-1]]
    )
    real_u = uniq & (s < sentval)  # NaN compares False too
    U = jnp.sum(real_u).astype(jnp.int32)

    def dict_path():
        targets = jnp.arange(1, D + 1, dtype=jnp.int32)
        slot = jnp.arange(D, dtype=jnp.int32)
        ranks = jnp.cumsum(real_u.astype(jnp.int32))
        pos = jnp.searchsorted(ranks, targets)
        entries = s[jnp.clip(pos, 0, B - 1)]
        full = jnp.concatenate(
            [entries, jnp.stack([sentval, nan_entry])]
        )
        valid = jnp.concatenate(
            [slot < U, jnp.stack([sent_flag, nan_flag])]
        )
        h1, h2 = hash_pair_numeric(full)
        return registers_from_hash_pair(h1, h2, valid)

    def scatter_path():
        return _scatter_column(xc, maskc)

    return jax.lax.cond(U <= D, dict_path, scatter_path)


def dedup_column_registers_from_sorted(
    s: jnp.ndarray,  # (B,) PRE-SORTED keys: invalid/non-finite -> +inf
    xc: jnp.ndarray,  # (B,) raw values (flag probes + fallback scatter)
    maskc: jnp.ndarray,  # (B,) validity
) -> jnp.ndarray:
    """(M,) batch registers from an ALREADY-SORTED key array — the
    KLL group's masked f32 sort (engine/vectorize._kll_sorted_stack),
    which maps nulls AND every non-finite value to the +inf sentinel.
    The three non-finite values (+inf, -inf, NaN) are therefore absent
    from the unique run and re-enter as flagged extra dictionary
    slots, probed from the raw column. Bit-identity caveats match
    dedup_column_registers (canonical-NaN collapse).

    INTEGER columns may ride the same f32 pool when the planner has
    proven their range fits the 24-bit mantissa (f32 cast exact):
    dictionary entries cast BACK to the raw dtype before hashing, so
    they take hash_pair_numeric's integral path bit-identically to the
    per-row scatter; the non-finite extras are impossible for int data
    (their flags are always False) and their cast garbage is masked."""
    (B,) = s.shape
    D = min(DEDUP_DICT_CAP, B)
    sentval = jnp.asarray(jnp.inf, s.dtype)
    uniq = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), s[1:] != s[:-1]]
    )
    real_u = uniq & (s < sentval)
    U = jnp.sum(real_u).astype(jnp.int32)
    integral = not jnp.issubdtype(xc.dtype, jnp.floating)
    if integral:
        false = jnp.asarray(False)
        pos_inf = neg_inf = nan_flag = false
    else:
        pos_inf = jnp.any((xc == jnp.inf) & maskc)
        neg_inf = jnp.any((xc == -jnp.inf) & maskc)
        nan_flag = jnp.any(jnp.isnan(xc) & maskc)

    def dict_path():
        targets = jnp.arange(1, D + 1, dtype=jnp.int32)
        slot = jnp.arange(D, dtype=jnp.int32)
        ranks = jnp.cumsum(real_u.astype(jnp.int32))
        pos = jnp.searchsorted(ranks, targets)
        entries = s[jnp.clip(pos, 0, B - 1)]
        extras = jnp.asarray([jnp.inf, -jnp.inf, jnp.nan], s.dtype)
        full = jnp.concatenate([entries, extras]).astype(xc.dtype)
        valid = jnp.concatenate(
            [slot < U, jnp.stack([pos_inf, neg_inf, nan_flag])]
        )
        h1, h2 = hash_pair_numeric(full)
        return registers_from_hash_pair(h1, h2, valid)

    def scatter_path():
        return _scatter_column(xc, maskc)

    return jax.lax.cond(U <= D, dict_path, scatter_path)


def gated_column_registers_from_sorted(
    s: jnp.ndarray,  # (B,) shared-pool sorted f32 keys for this column
    xc: jnp.ndarray,  # (B,) raw values
    maskc: jnp.ndarray,  # (B,) validity
    prev_registers: jnp.ndarray,  # (M,) carried state for this column
) -> jnp.ndarray:
    """Runtime-widened sorted-dedup dispatch for ONE column the planner
    could NOT statically qualify (the O(1) range probe failed, or the
    declared range was too wide to prove anything). The column still
    rides the shared KLL sort — already paid for — and takes the dict
    path only when BOTH runtime checks pass:

    - the carried-register linear-counting estimate says
      mid-cardinality (``dedup_gate``), and
    - for integer data, every valid value in THIS batch fits the f32
      24-bit mantissa, so the pool's f32 sort keys are exact and the
      dict entries round-trip to the raw dtype bit-identically.

    Correctness never depends on the gate being right: a mispredicted
    estimate (actual batch U > D) falls back to the scatter INSIDE
    dedup_column_registers_from_sorted, and a non-qualifying batch
    pays only the two cheap checks on top of its plain scatter."""
    gate = dedup_gate(prev_registers)
    if jnp.issubdtype(xc.dtype, jnp.floating):
        qualifies = gate
    else:
        lim = 1 << 24  # f32 mantissa: int casts are exact in ±2^24
        xi = xc.astype(jnp.int64)
        in_mantissa = jnp.all(
            jnp.where(maskc, (xi >= -lim) & (xi <= lim), True)
        )
        qualifies = gate & in_mantissa
    return jax.lax.cond(
        qualifies,
        lambda: dedup_column_registers_from_sorted(s, xc, maskc),
        lambda: _scatter_column(xc, maskc),
    )


def registers_from_sorted_dedup_stacked(
    x: jnp.ndarray,  # (C, B) values, one dtype
    masks: jnp.ndarray,  # (C, B) validity
) -> jnp.ndarray:
    """(C, M) batch registers, every column through the sorted-dedup
    builder (no gating) — the differential-test surface for
    dedup_column_registers."""
    return jnp.stack(
        [
            dedup_column_registers(x[c], masks[c])
            for c in range(x.shape[0])
        ]
    )


def numeric_registers_adaptive(
    x: jnp.ndarray,  # (C, B) values
    masks: jnp.ndarray,  # (C, B) validity
    prev_registers: jnp.ndarray,  # (C, M) carried state
) -> jnp.ndarray:
    """THE numeric register builder. Default: ONE stacked flat scatter
    for the whole group. When the carried state says ANY column is
    mid-cardinality, the group switches to per-column dispatch where
    each gated column pays ITS OWN sort + unique compaction (~8 ms vs
    ~15 ms scatter at B=2^21) and ungated columns keep a plain scatter
    — a high-cardinality column never pays for its mid-card neighbors
    (the r5 batched-sort-for-the-whole-group variant measured a net
    LOSS on mixed groups for exactly that reason). Both layouts
    scatter at the same per-element rate (PERF.md r4: banked splits ==
    stacked)."""
    if not _dedup_supported(x.dtype):
        h1, h2 = hash_pair_numeric(x)
        return registers_from_hash_pair_stacked(h1, h2, masks)
    C = x.shape[0]
    gate = dedup_gate(prev_registers)

    def scatter_all():
        h1, h2 = hash_pair_numeric(x)
        return registers_from_hash_pair_stacked(h1, h2, masks)

    def per_column():
        outs = []
        for c in range(C):
            outs.append(
                jax.lax.cond(
                    gate[c],
                    lambda c=c: dedup_column_registers(x[c], masks[c]),
                    lambda c=c: _scatter_column(x[c], masks[c]),
                )
            )
        return jnp.stack(outs)

    return jax.lax.cond(jnp.any(gate), per_column, scatter_all)


def _scatter_column(xc: jnp.ndarray, maskc: jnp.ndarray) -> jnp.ndarray:
    h1, h2 = hash_pair_numeric(xc)
    return registers_from_hash_pair(h1, h2, maskc)


_Q = 32  # h2 supplies 32 bits => register ranks 0..Q+1


def _sigma(x: float) -> float:
    """Ertl's σ series (linear-counting correction term)."""
    if x == 1.0:
        return float("inf")
    y = 1.0
    z = x
    while True:
        x = x * x
        z_prev = z
        z = z + x * y
        y = y + y
        if z == z_prev:
            return z


def _tau(x: float) -> float:
    """Ertl's τ series (saturated-register correction term)."""
    if x == 0.0 or x == 1.0:
        return 0.0
    y = 1.0
    z = 1.0 - x
    while True:
        x = np.sqrt(x)
        z_prev = z
        y = 0.5 * y
        z = z - (1.0 - x) ** 2 * y
        if z == z_prev:
            return z / 3.0


def estimate(registers: np.ndarray) -> float:
    """Ertl's improved raw estimator ("New cardinality estimation
    algorithms for HyperLogLog sketches", Ertl 2017, Alg. 6): unbiased
    across the whole range with NO empirical bias tables and no
    linear-counting/raw switchover — strictly better than the original
    HLL estimator's biased transition region (~2.5m..5m), which is what
    the reference corrects with HLL++'s lookup tables."""
    registers = np.asarray(registers)
    m = float(M)
    counts = np.bincount(
        registers.astype(np.int64), minlength=_Q + 2
    ).astype(np.float64)
    z = m * _tau(1.0 - counts[_Q + 1] / m)
    for k in range(_Q, 0, -1):
        z = 0.5 * (z + counts[k])
    z = z + m * _sigma(counts[0] / m)
    alpha_inf = 1.0 / (2.0 * np.log(2.0))
    return float(alpha_inf * m * m / z)
