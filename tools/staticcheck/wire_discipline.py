"""Wire-discipline analyzer: the data layer stays on the host, and
wire dtype decisions stay out of per-batch loops.

The wire diet (docs/PERF.md) only works if layering holds:

``wire-discipline`` — two checks over the wire path:

1. Modules under ``deequ_tpu/data/`` may not call ``jax.device_put``
   or ``jax.jit`` (or ``jax.pmap``). Device placement belongs to the
   engine — a data-layer put bypasses the wire pack (masks at 1
   bit/row, per-column codecs, transfer accounting) and ships fat
   unencoded buffers. The handful of deliberate resident-path helpers
   in ``data/table.py`` (device-built row masks, the fused mask
   unpack, the chunk-cache put that IS the resident wire) carry
   reasoned waivers.

2. In wire-path modules (``deequ_tpu/data/table.py``,
   ``deequ_tpu/data/parquet.py``, ``deequ_tpu/engine/scan.py``,
   ``deequ_tpu/engine/wire.py``), the wire-narrowing helpers
   (``narrow_int64_values``, ``narrow_codes``,
   ``narrowest_int_dtype``) must not be called lexically inside a
   ``for``/``while`` loop. A per-batch narrowing decision makes
   streamed batch dtypes depend on batch CONTENT, which breaks the
   fixed-layout no-recompile contract (``narrow_int64_values``
   docstring): one cold batch widens the wire and retraces the fused
   scan. Narrowing is decided once per run — from parquet statistics,
   a first-batch probe, or the whole materialized column.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Sequence, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

DATA_PREFIX = "deequ_tpu/data/"
#: jax entry points that place or compile for a device
DEVICE_CALLS = frozenset({"jax.device_put", "jax.jit", "jax.pmap"})
WIRE_PATH_FILES = (
    "deequ_tpu/data/table.py",
    "deequ_tpu/data/parquet.py",
    "deequ_tpu/engine/scan.py",
    "deequ_tpu/engine/wire.py",
)
#: dtype-deciding helpers; calling one per batch breaks the
#: fixed-layout contract
NARROWING_TAILS = frozenset(
    {"narrow_int64_values", "narrow_codes", "narrowest_int_dtype"}
)


class _WireScanner(ast.NodeVisitor):
    """One pass over a module: device-placement calls, and narrowing
    calls tagged with the lexical loop depth at the call site."""

    def __init__(self) -> None:
        self.loop_depth = 0
        self.device_calls: List[Tuple[str, int]] = []
        self.looped_narrowing: List[Tuple[str, int]] = []

    def _visit_loop(self, node: ast.AST) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    # a nested def inside a loop body runs per iteration only if called
    # there; but in this codebase closures defined in loops are rare
    # and a narrowing call inside one is exactly as per-batch as an
    # inline call, so the loop depth deliberately carries through.

    def visit_Call(self, node: ast.Call) -> None:
        callee = dotted_name(node.func)
        if callee:
            if callee in DEVICE_CALLS or callee.endswith(".device_put"):
                self.device_calls.append((callee, node.lineno))
            tail = callee.split(".")[-1]
            if tail in NARROWING_TAILS and self.loop_depth > 0:
                self.looped_narrowing.append((tail, node.lineno))
        self.generic_visit(node)


class WireDisciplineAnalyzer(Analyzer):
    name = "wire"
    rules = ("wire-discipline",)
    description = (
        "device placement calls in the host-only data layer; "
        "per-batch wire-narrowing decisions in loops"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            in_data = sf.rel.startswith(DATA_PREFIX)
            in_wire_path = sf.rel in WIRE_PATH_FILES
            if not (in_data or in_wire_path) or sf.tree is None:
                continue
            scanner = _WireScanner()
            scanner.visit(sf.tree)
            if in_data:
                for callee, line in scanner.device_calls:
                    yield Finding(
                        rule="wire-discipline",
                        path=sf.rel,
                        line=line,
                        message=(
                            f"'{callee}' in the host-only data layer: "
                            "device placement belongs to the engine's "
                            "wire (pack -> put -> fused unpack); a "
                            "data-layer put ships unencoded buffers "
                            "and bypasses transfer accounting"
                        ),
                        symbol=callee,
                    )
            if in_wire_path:
                for tail, line in scanner.looped_narrowing:
                    yield Finding(
                        rule="wire-discipline",
                        path=sf.rel,
                        line=line,
                        message=(
                            f"'{tail}' called inside a loop: a "
                            "per-batch narrowing decision makes "
                            "streamed dtypes content-dependent and "
                            "retraces the fused scan (fixed-layout "
                            "contract, narrow_int64_values docstring); "
                            "decide the wire dtype once per run"
                        ),
                        symbol=tail,
                    )


register(WireDisciplineAnalyzer())
