"""RunListener: callback API over run execution, analogous to Spark's
``SparkListener``/deequ's reliance on the Spark UI (SURVEY.md §5.1).

Listeners observe; they must never steer. Every callback is dispatched
best-effort — an exception inside a listener is swallowed (recorded on
the ``telemetry.listener_errors`` counter) so a broken dashboard hook
cannot fail a verification run.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class RunListener:
    """Subclass and override the callbacks you care about.

    Callback timing:

    - ``on_run_start/on_run_end`` — one analysis/verification run
      (``AnalysisRunner.do_analysis_run`` granularity)
    - ``on_pass_start/on_pass_end`` — one engine pass (fused scan,
      frequency pass, direct analyzers)
    - ``on_analyzer_computed`` — each (analyzer, metric) as the run
      assembles its AnalyzerContext (failure metrics included)
    - ``on_check_evaluated`` — each (check, check_result) as the
      VerificationSuite evaluates checks
    - ``on_engine_event`` — structured engine events (``scan_phases``
      wall decomposition, ``grouping_spill`` fallbacks, ...)
    """

    def on_run_start(self, run_id: int, name: str) -> None:
        pass

    def on_run_end(self, run_id: int, name: str, summary: Optional[Dict[str, Any]]) -> None:
        pass

    def on_pass_start(self, name: str, rows: int, num_analyzers: int) -> None:
        pass

    def on_pass_end(
        self, name: str, wall_s: float, rows: int, num_analyzers: int
    ) -> None:
        pass

    def on_analyzer_computed(self, analyzer: Any, metric: Any) -> None:
        pass

    def on_check_evaluated(self, check: Any, result: Any) -> None:
        pass

    def on_engine_event(self, event: Dict[str, Any]) -> None:
        pass


class CollectingRunListener(RunListener):
    """Records every callback (tests, notebooks, debugging)."""

    def __init__(self) -> None:
        self.run_starts: List[tuple] = []
        self.run_ends: List[tuple] = []
        self.pass_starts: List[tuple] = []
        self.pass_ends: List[tuple] = []
        self.analyzers_computed: List[tuple] = []
        self.checks_evaluated: List[tuple] = []
        self.engine_events: List[Dict[str, Any]] = []

    def on_run_start(self, run_id, name):
        self.run_starts.append((run_id, name))

    def on_run_end(self, run_id, name, summary):
        self.run_ends.append((run_id, name, summary))

    def on_pass_start(self, name, rows, num_analyzers):
        self.pass_starts.append((name, rows, num_analyzers))

    def on_pass_end(self, name, wall_s, rows, num_analyzers):
        self.pass_ends.append((name, wall_s, rows, num_analyzers))

    def on_analyzer_computed(self, analyzer, metric):
        self.analyzers_computed.append((analyzer, metric))

    def on_check_evaluated(self, check, result):
        self.checks_evaluated.append((check, result))

    def on_engine_event(self, event):
        self.engine_events.append(event)
