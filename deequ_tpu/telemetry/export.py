"""Structured export helpers: summary serde, summary merging, and
JSONL artifact reading.

The *summary* is the per-run dict produced by ``RunCapture.summary``
(runtime.py) and attached to ``AnalyzerContext``/``VerificationResult``
— plain JSON-serializable data by construction, so persistence is
``json.dumps``/``loads`` with a round-trip identity (tested in
tests/test_telemetry.py).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence


def summary_to_json(summary: Dict[str, Any], indent: int = 2) -> str:
    return json.dumps(summary, indent=indent, default=str)


def summary_from_json(text: str) -> Dict[str, Any]:
    return json.loads(text)


def merge_summaries(
    summaries: Sequence[Optional[Dict[str, Any]]],
) -> Optional[Dict[str, Any]]:
    """Fold several per-run summaries (e.g. the profiler's passes over
    the same dataset) into one: walls add, pass/event/span lists
    concatenate in order, counter deltas add. ``None`` entries are
    skipped; all-None means no telemetry was captured."""
    present = [s for s in summaries if s]
    if not present:
        return None
    if len(present) == 1:
        return present[0]
    counters: Dict[str, float] = {}
    for s in present:
        for k, v in s.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
    return {
        "run_id": present[0].get("run_id"),
        "run_ids": [s.get("run_id") for s in present],
        "name": present[0].get("name", "run"),
        "wall_s": sum(s.get("wall_s", 0.0) for s in present),
        "passes": [p for s in present for p in s.get("passes", [])],
        "events": [e for s in present for e in s.get("events", [])],
        "spans": [sp for s in present for sp in s.get("spans", [])],
        "counters": counters,
    }


def summarize_phases(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Sum ``scan_phases`` events into one wall-decomposition dict (the
    shape bench.py and tools/obs_report.py report)."""
    out: Dict[str, Any] = {}
    for e in events:
        if e.get("event") != "scan_phases":
            continue
        for k, v in e.items():
            if isinstance(v, float):
                out[k] = out.get(k, 0.0) + v
        out["scan_passes"] = out.get("scan_passes", 0) + 1
    return {
        k: (round(v, 3) if isinstance(v, float) else v)
        for k, v in out.items()
    }


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL artifact (skips unparseable lines — the
    log may be appended by several processes)."""
    records: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records
