"""Checkpoint-conserving preemption + queue-driven autoscaling
(docs/SERVICE.md "Preemption and autoscaling"): evidence gating, the
victim policy, requeue/resume semantics, journal recovery across a
kill, the real-engine bit-equality differential, and the autoscale
control loop — scheduling behavior on stub executors and fake clocks,
engine behavior on small real datasets."""

import threading
import time

import numpy as np
import pytest

from deequ_tpu.engine.deadline import (
    ManualClock,
    RunCancelled,
    ScanInterruption,
)
from deequ_tpu.service import (
    AutoscaleController,
    Priority,
    PreemptionController,
    RunHandle,
    RunJournal,
    RunQueue,
    RunRequest,
    RunState,
    RunTicket,
    VerificationService,
    preempt_checkpoint_evidence,
    run_cancel_token,
)
from deequ_tpu.service.autoscale import (
    BATCH_WAIT,
    IDLE_ROUNDS_BEFORE_SCALE_DOWN,
    INTERACTIVE_WAIT,
    interval_p99,
)
from deequ_tpu.service.coalesce import CoalescePolicy
from deequ_tpu.service.preempt import is_preempt_reason, preempt_reason
from deequ_tpu.telemetry import get_telemetry


def _ticket(priority=Priority.BATCH, run_id="run-x", seq=0, tenant="acme"):
    handle = RunHandle(run_id, tenant, priority)
    return RunTicket(seq=seq, handle=handle, payload=None, budget=None)


def _spin_until(predicate, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


def _count(name):
    return get_telemetry().counter(name).value


def _events(name):
    return [
        e for e in get_telemetry().recent() if e.get("event") == name
    ]


class _FakeResult:
    def __init__(self, interruption=None):
        self.interruption = interruption
        self.telemetry = None
        self.metrics = {}


def _preempted_result(token, checkpointed=True, batch_index=3):
    return _FakeResult(
        interruption=ScanInterruption(
            kind="cancelled",
            reason=token.reason or "",
            batch_index=batch_index,
            row_offset=batch_index * 1000,
            checkpointed=checkpointed,
        )
    )


# ---------------------------------------------------------------------------
# evidence gating (preempt_checkpoint_evidence)
# ---------------------------------------------------------------------------


class TestEvidence:
    def _armed(self):
        ticket = _ticket()
        controller = PreemptionController(clock=ManualClock())
        record = controller.register([ticket])
        assert controller.preempt_for("demand-1")
        return ticket, controller, record

    def test_no_request_means_no_evidence(self):
        ticket = _ticket()
        PreemptionController(clock=ManualClock()).register([ticket])
        outcome = _FakeResult(
            interruption=ScanInterruption(
                kind="cancelled",
                reason=preempt_reason("run-x", "d"),
                checkpointed=True,
            )
        )
        assert preempt_checkpoint_evidence(ticket, outcome) is None

    def test_preempt_cancel_interruption_is_evidence_and_cached(self):
        ticket, _c, _r = self._armed()
        outcome = _preempted_result(ticket.preempt_token)
        evidence = preempt_checkpoint_evidence(ticket, outcome)
        assert evidence is outcome.interruption
        assert evidence.checkpointed is True
        # the no-outcome form reads the cached verdict (the lease
        # revocation call site relies on this)
        assert preempt_checkpoint_evidence(ticket) is evidence

    def test_user_cancel_wins_over_preemption(self):
        ticket, _c, _r = self._armed()
        ticket.handle.cancel_token.cancel("changed my mind")
        outcome = _preempted_result(ticket.preempt_token)
        assert preempt_checkpoint_evidence(ticket, outcome) is None

    def test_precancel_runcancelled_yields_unchecked_evidence(self):
        ticket, _c, _r = self._armed()
        exc = RunCancelled(ticket.preempt_token.reason)
        evidence = preempt_checkpoint_evidence(ticket, exc)
        assert evidence is not None
        assert evidence.checkpointed is False
        assert evidence.batch_index == 0

    def test_foreign_cancel_reason_is_not_evidence(self):
        ticket, _c, _r = self._armed()
        outcome = _FakeResult(
            interruption=ScanInterruption(
                kind="cancelled", reason="deadline shim", checkpointed=True
            )
        )
        assert preempt_checkpoint_evidence(ticket, outcome) is None

    def test_reason_roundtrip(self):
        reason = preempt_reason("victim-1", "demand-9")
        assert is_preempt_reason(reason)
        assert "victim-1" in reason and "demand-9" in reason
        assert not is_preempt_reason("cancelled")
        assert not is_preempt_reason(None)


# ---------------------------------------------------------------------------
# victim policy (PreemptionController)
# ---------------------------------------------------------------------------


class TestVictimPolicy:
    def test_solo_batch_is_eligible_and_token_fires(self):
        clock = ManualClock()
        controller = PreemptionController(clock=clock)
        ticket = _ticket(run_id="victim")
        controller.register([ticket])
        before = _count("service.preemptions")
        assert controller.preempt_for("needy") is True
        assert ticket.preempt_requested is True
        assert ticket.preemptions == 1
        assert ticket.preempt_token.cancelled
        assert is_preempt_reason(ticket.preempt_token.reason)
        # the handle's own token is untouched: only this attempt dies
        assert not ticket.handle.cancel_token.cancelled
        assert _count("service.preemptions") == before + 1
        # an already-requested victim is not preempted twice
        assert controller.preempt_for("needy-2") is False

    def test_coalesced_group_is_never_a_victim(self):
        controller = PreemptionController(clock=ManualClock())
        group = [
            _ticket(run_id="m1"),
            _ticket(run_id="m2", seq=1),
        ]
        controller.register(group)
        assert controller.preempt_for("needy") is False

    def test_interactive_run_is_never_a_victim(self):
        controller = PreemptionController(clock=ManualClock())
        controller.register(
            [_ticket(priority=Priority.INTERACTIVE, run_id="i")]
        )
        assert controller.preempt_for("needy") is False

    def test_youngest_victim_chosen(self):
        clock = ManualClock()
        controller = PreemptionController(clock=clock)
        old = _ticket(run_id="old")
        controller.register([old])
        clock.advance(5.0)
        young = _ticket(run_id="young", seq=7)
        controller.register([young])
        assert controller.preempt_for("needy") is True
        assert young.preempt_requested and not old.preempt_requested

    def test_max_preemptions_bounds_livelock(self):
        controller = PreemptionController(
            clock=ManualClock(), max_preemptions_per_run=2
        )
        ticket = _ticket(run_id="twice")
        ticket.preemptions = 2
        controller.register([ticket])
        # at the bound the run is no longer a victim: it runs to
        # completion however long interactive pressure lasts
        assert controller.preempt_for("needy") is False

    def test_deregister_removes_group(self):
        controller = PreemptionController(clock=ManualClock())
        record = controller.register([_ticket()])
        controller.deregister(record)
        assert controller.preempt_for("needy") is False
        assert controller.snapshot()["running_groups"] == 0


# ---------------------------------------------------------------------------
# queue requeue semantics
# ---------------------------------------------------------------------------


class TestRequeue:
    def test_requeue_preserves_seq_restamps_submit(self):
        clock = ManualClock()
        q = RunQueue(clock=clock)
        ticket = _ticket(run_id="back")
        q.push(ticket)
        seq_at_submit = ticket.seq  # the queue stamps seq at push
        popped = q.pop(should_stop=lambda: True)
        assert popped is ticket
        clock.advance(9.0)
        assert q.requeue(ticket) is True
        assert ticket.seq == seq_at_submit  # place in line is conserved
        assert ticket.submitted_at == clock.now()  # new wait leg
        assert ticket.handle.status == RunState.QUEUED
        again = q.pop(should_stop=lambda: True)
        assert again is ticket

    def test_requeued_resumes_ahead_of_later_batch(self):
        q = RunQueue(clock=ManualClock())
        victim = _ticket(run_id="victim", seq=1)
        q.push(victim)
        assert q.pop(should_stop=lambda: True) is victim
        later = _ticket(run_id="later", seq=2)
        q.push(later)
        q.requeue(victim)
        # original seq orders the victim ahead of anything submitted
        # after it — preemption changes WHEN it runs, not its place
        assert q.pop(should_stop=lambda: True) is victim

    def test_requeue_into_closed_queue_fails(self):
        q = RunQueue(clock=ManualClock())
        ticket = _ticket(run_id="late")
        q.push(ticket)
        q.pop(should_stop=lambda: True)
        q.close()
        assert q.requeue(ticket) is False


# ---------------------------------------------------------------------------
# service-level preempt -> requeue -> resume (stub executors)
# ---------------------------------------------------------------------------


class TestServicePreemption:
    def _request(self, tenant="acme", priority=Priority.BATCH,
                 dataset_key="shared"):
        return RunRequest(
            tenant=tenant,
            checks=(),
            dataset_key=dataset_key,
            dataset_factory=lambda: None,
            priority=priority,
        )

    def _preemptable_execute(self, resume_release=None):
        """BATCH first attempts block until preempted; resumed
        attempts (and INTERACTIVE runs) complete immediately, unless
        ``resume_release`` gates the resumed leg too."""

        def execute(ticket):
            token = run_cancel_token(ticket)
            if ticket.handle.priority >= Priority.BATCH:
                if ticket.preemptions == 0:
                    assert token.wait(timeout=30)
                    return _preempted_result(token)
                if resume_release is not None:
                    assert resume_release.wait(timeout=30)
                    if token.cancelled:
                        return _preempted_result(token)
            return _FakeResult()

        return execute

    def test_full_preempt_requeue_resume_cycle(self, tmp_path):
        before = {
            name: _count(name)
            for name in (
                "service.preemptions",
                "service.preempt_requeues",
                "service.preempt_resumes",
                "service.preempted_batches_conserved",
            )
        }
        svc = VerificationService(
            workers=1, clock=ManualClock(),
            execute=self._preemptable_execute(),
            preemption=True, journal_dir=str(tmp_path),
        ).start()
        try:
            batch = svc.submit(self._request(priority=Priority.BATCH))
            assert _spin_until(lambda: batch.status == RunState.RUNNING)
            quick = svc.submit(
                self._request(
                    tenant="globex", priority=Priority.INTERACTIVE,
                    dataset_key="q",
                )
            )
            # the interactive run preempts through the saturated pool
            assert quick.wait(timeout=15)
            assert quick.status == RunState.DONE
            # the victim resumes and completes
            assert batch.wait(timeout=15)
            assert batch.status == RunState.DONE
            assert batch.result(timeout=0).interruption is None
        finally:
            svc.stop(drain=False, timeout=10)
        assert _count("service.preemptions") == before[
            "service.preemptions"
        ] + 1
        assert _count("service.preempt_requeues") == before[
            "service.preempt_requeues"
        ] + 1
        assert _count("service.preempt_resumes") == before[
            "service.preempt_resumes"
        ] + 1
        # the stub's evidence said batch_index=3: three batches crossed
        # the preemption without recompute
        assert _count("service.preempted_batches_conserved") == before[
            "service.preempted_batches_conserved"
        ] + 3
        # the decision trail: requested -> preempted -> resumed
        assert _events("service_run_preempt_requested")
        assert _events("service_run_preempted")
        assert _events("service_run_resumed")
        # the journal holds the write-ahead bracket in order
        journal = RunJournal(str(tmp_path))
        types = [r["type"] for r in journal.replay()
                 if r.get("run_id") == batch.run_id]
        assert "preempted" in types and "resumed" in types
        assert types.index("preempted") < types.index("resumed")
        assert types[-1] == "terminal"

    def test_queued_batch_is_not_preempted(self):
        release = threading.Event()

        def execute(ticket):
            if ticket.handle.priority == Priority.STANDARD:
                assert release.wait(timeout=30)
            return _FakeResult()

        before = _count("service.preemptions")
        svc = VerificationService(
            workers=1, clock=ManualClock(), execute=execute,
            preemption=True,
        ).start()
        try:
            blocker = svc.submit(
                self._request(priority=Priority.STANDARD)
            )
            assert _spin_until(
                lambda: blocker.status == RunState.RUNNING
            )
            # a QUEUED batch holds no capacity: it yields by skip, not
            # by cancellation, and is never a preemption victim
            parked = svc.submit(
                self._request(priority=Priority.BATCH, dataset_key="b")
            )
            quick = svc.submit(
                self._request(
                    tenant="globex", priority=Priority.INTERACTIVE,
                    dataset_key="q",
                )
            )
            # the running STANDARD group is not eligible either — the
            # interactive run waits its turn, nothing is preempted
            assert _count("service.preemptions") == before
            release.set()
            assert quick.wait(timeout=15)
            assert parked.wait(timeout=15)
            assert quick.status == RunState.DONE
            assert parked.status == RunState.DONE
        finally:
            release.set()
            svc.stop(drain=False, timeout=10)
        assert _count("service.preemptions") == before

    def test_preemption_cap_then_runs_to_completion(self):
        from deequ_tpu import config

        resume_release = threading.Event()
        before = _count("service.preemptions")
        with config.configure(service_preempt_max_per_run=1):
            svc = VerificationService(
                workers=1, clock=ManualClock(),
                execute=self._preemptable_execute(resume_release),
                preemption=True,
            ).start()
        try:
            batch = svc.submit(self._request(priority=Priority.BATCH))
            assert _spin_until(lambda: batch.status == RunState.RUNNING)
            first = svc.submit(
                self._request(
                    tenant="globex", priority=Priority.INTERACTIVE,
                    dataset_key="q1",
                )
            )
            assert first.wait(timeout=15)
            assert _count("service.preemptions") == before + 1
            # the victim is resuming (blocked on resume_release); at
            # the cap it is ineligible: a second interactive demand
            # preempts nothing and waits behind it
            assert _spin_until(lambda: batch.status == RunState.RUNNING)
            second = svc.submit(
                self._request(
                    tenant="globex", priority=Priority.INTERACTIVE,
                    dataset_key="q2",
                )
            )
            assert not second.wait(timeout=0.3)
            assert _count("service.preemptions") == before + 1
            resume_release.set()
            assert batch.wait(timeout=15)
            assert second.wait(timeout=15)
            assert batch.status == RunState.DONE
            assert second.status == RunState.DONE
        finally:
            resume_release.set()
            svc.stop(drain=False, timeout=10)

    def test_user_cancel_terminates_not_requeues(self):
        def execute(ticket):
            token = run_cancel_token(ticket)
            if ticket.handle.priority == Priority.BATCH:
                assert token.wait(timeout=30)
                return _FakeResult(
                    interruption=ScanInterruption(
                        kind="cancelled",
                        reason=token.reason or "",
                        checkpointed=True,
                    )
                )
            return _FakeResult()

        before = _count("service.preempt_requeues")
        svc = VerificationService(
            workers=1, clock=ManualClock(), execute=execute,
            preemption=True,
        ).start()
        try:
            batch = svc.submit(self._request(priority=Priority.BATCH))
            assert _spin_until(lambda: batch.status == RunState.RUNNING)
            batch.cancel("changed my mind")
            assert batch.wait(timeout=15)
            # a client cancel rides the handle token THROUGH the
            # per-attempt preempt token: the run terminates CANCELLED
            # with its partial result — it is not silently requeued
            assert batch.status == RunState.CANCELLED
        finally:
            svc.stop(drain=False, timeout=10)
        assert _count("service.preempt_requeues") == before

    def test_off_by_default_is_inert(self):
        seen = {}

        def execute(ticket):
            seen["token_is_handle"] = (
                run_cancel_token(ticket) is ticket.handle.cancel_token
            )
            seen["preempt_token"] = ticket.preempt_token
            return _FakeResult()

        svc = VerificationService(
            workers=1, clock=ManualClock(), execute=execute,
        ).start()
        try:
            assert svc.preemption is None
            assert svc.autoscaler is None
            assert svc.scheduler.preemption is None
            handle = svc.submit(self._request())
            assert handle.wait(timeout=15)
            assert handle.status == RunState.DONE
            # no controller, no per-attempt tokens: the executor sees
            # bit-for-bit the pre-preemption cancel plumbing
            assert seen["token_is_handle"] is True
            assert seen["preempt_token"] is None
            assert "preemption" not in svc.health()
            assert "autoscale" not in svc.health()
        finally:
            svc.stop(drain=False, timeout=10)

    def test_health_reports_preemption_plane(self):
        svc = VerificationService(
            workers=1, clock=ManualClock(),
            execute=lambda t: _FakeResult(),
            preemption=True, autoscale=True,
        ).start()
        try:
            payload = svc.health()
            assert payload["preemption"]["running_groups"] == 0
            assert "preemptions" in payload["preemption"]
            assert payload["autoscale"]["workers"] == 1
        finally:
            svc.stop(drain=False, timeout=10)


# ---------------------------------------------------------------------------
# journal bracket + kill-between-preempt-and-resume recovery
# ---------------------------------------------------------------------------


class TestPreemptionRecovery:
    def test_pending_runs_tracks_preemption_bracket(self, tmp_path):
        journal = RunJournal(str(tmp_path))
        journal.record_submitted(
            "r1", tenant="acme", priority=Priority.BATCH
        )
        journal.record_started("r1")
        journal.record_preempted(
            "r1", reason=preempt_reason("r1", "d"),
            batch_index=4, row_offset=4096, checkpointed=True,
        )
        entry = journal.pending_runs()["r1"]
        assert entry["preempted"] is True
        assert entry["preempt_count"] == 1
        assert entry["last_preemption"]["batch_index"] == 4
        journal.record_resumed("r1", preemptions=1)
        entry = journal.pending_runs()["r1"]
        assert entry["preempted"] is False
        assert entry["preempt_count"] == 1
        journal.record_terminal("r1", "done")
        assert "r1" not in journal.pending_runs()

    def test_killed_between_preempt_and_resume_recovers(self, tmp_path):
        # the dead service got exactly this far: victim preempted,
        # write-ahead record landed, process died BEFORE the requeued
        # ticket executed — no resumed record, no terminal record
        dead = RunJournal(str(tmp_path))
        dead.record_submitted(
            "victim-1", tenant="acme", priority=Priority.BATCH,
            dataset_key="shared",
        )
        dead.record_started("victim-1")
        dead.record_preempted(
            "victim-1", reason=preempt_reason("victim-1", "demand"),
            batch_index=7, row_offset=7168, checkpointed=True,
        )

        seen = {}

        def resolve(run_id, entry):
            seen[run_id] = entry
            return RunRequest(
                tenant=entry["tenant"],
                checks=(),
                dataset_key=entry.get("dataset_key"),
                dataset_factory=lambda: None,
                priority=entry.get("priority", Priority.BATCH),
            )

        svc = VerificationService(
            workers=1, clock=ManualClock(),
            execute=lambda t: _FakeResult(),
            preemption=True, journal_dir=str(tmp_path),
        )
        handles = svc.recover(resolve)
        svc.start()
        try:
            assert [h.run_id for h in handles] == ["victim-1"]
            # the resolver saw the preemption bracket: the run is
            # recovered as preempted-not-yet-resumed
            assert seen["victim-1"]["preempted"] is True
            assert seen["victim-1"]["last_preemption"][
                "batch_index"
            ] == 7
            assert handles[0].wait(timeout=15)
            assert handles[0].status == RunState.DONE
        finally:
            svc.stop(drain=False, timeout=10)


# ---------------------------------------------------------------------------
# autoscaling control loop
# ---------------------------------------------------------------------------


class _FakeScheduler:
    def __init__(self, workers=1, interactive_reserve=0, window_s=0.0):
        self.workers = workers
        self.interactive_reserve = interactive_reserve
        self.coalesce = CoalescePolicy(
            enabled=window_s > 0, window_s=window_s
        )
        self.queue = self
        self.resizes = []

    def depth(self):
        return 0

    def resize(self, workers=None, interactive_reserve=None):
        target = self.workers if workers is None else max(1, int(workers))
        reserve = (
            self.interactive_reserve
            if interactive_reserve is None
            else max(0, int(interactive_reserve))
        )
        self.interactive_reserve = min(reserve, target - 1)
        self.workers = target
        self.resizes.append((self.workers, self.interactive_reserve))


class TestAutoscale:
    def test_interval_p99_diffs_cumulative_snapshots(self):
        prev = {"count": 10, "max": 2.0, "buckets": {0.1: 8, 1.0: 10}}
        cur = {"count": 110, "max": 2.0, "buckets": {0.1: 9, 1.0: 110}}
        # 100 interval observations, 99% of them under the 1.0 bound
        assert interval_p99(prev, cur) == 1.0
        assert interval_p99(cur, cur) is None  # empty interval
        assert interval_p99(None, prev) == 1.0

    def test_interval_p99_beyond_top_bucket_uses_max(self):
        cur = {"count": 5, "max": 42.0, "buckets": {0.1: 0, 1.0: 0}}
        assert interval_p99(None, cur) == 42.0

    def test_scale_up_on_interactive_pressure(self):
        sched = _FakeScheduler(workers=1)
        ctl = AutoscaleController(
            sched, clock=ManualClock(), max_workers=4,
            target_interactive_p99_s=0.5,
        )
        ctl.step()  # baseline: absorb whatever history the registry holds
        hist = get_telemetry().metrics.histogram(INTERACTIVE_WAIT)
        for _ in range(5):
            hist.observe(3.0)
        adjustments = ctl.step()
        assert sched.workers == 2
        assert sched.interactive_reserve == 1
        knobs = {a["knob"] for a in adjustments}
        assert "workers" in knobs and "interactive_reserve" in knobs
        assert all("reason" in a for a in adjustments)
        # one notch per decision, not a jump to max
        assert sched.workers < 4

    def test_scale_down_needs_consecutive_idle_rounds(self):
        sched = _FakeScheduler(workers=3)
        ctl = AutoscaleController(
            sched, clock=ManualClock(), min_workers=1, max_workers=4
        )
        ctl.step()  # baseline
        for _ in range(IDLE_ROUNDS_BEFORE_SCALE_DOWN - 1):
            assert ctl.step() == []
            assert sched.workers == 3  # hysteresis holds
        adjustments = ctl.step()
        assert sched.workers == 2
        assert adjustments[0]["knob"] == "workers"

    def test_pressure_resets_idle_hysteresis(self):
        sched = _FakeScheduler(workers=2)
        ctl = AutoscaleController(
            sched, clock=ManualClock(), max_workers=4,
            target_interactive_p99_s=0.5,
        )
        ctl.step()
        ctl.step()  # idle round 1
        get_telemetry().metrics.histogram(INTERACTIVE_WAIT).observe(9.0)
        ctl.step()  # pressure: scales up AND resets the idle streak
        assert sched.workers == 3
        for _ in range(IDLE_ROUNDS_BEFORE_SCALE_DOWN - 1):
            ctl.step()
        assert sched.workers == 3  # not enough idle rounds yet

    def test_window_shrinks_under_batch_starvation_and_restores(self):
        sched = _FakeScheduler(workers=2, window_s=0.2)
        ctl = AutoscaleController(sched, clock=ManualClock())
        ctl.step()  # baseline
        hist = get_telemetry().metrics.histogram(BATCH_WAIT)
        for _ in range(4):
            hist.observe(5.0)  # p99 >> 4x the 0.2s window
        adjustments = ctl.step()
        assert sched.coalesce.window_s == pytest.approx(0.1)
        assert any(
            a["knob"] == "coalesce_window_s" for a in adjustments
        )
        # waits subside -> the window doubles back toward its base,
        # never past it
        ctl.step()
        assert sched.coalesce.window_s == pytest.approx(0.2)
        ctl.step()
        assert sched.coalesce.window_s == pytest.approx(0.2)

    def test_autoscale_emits_decision_events(self):
        sched = _FakeScheduler(workers=1)
        ctl = AutoscaleController(
            sched, clock=ManualClock(), max_workers=2,
            target_interactive_p99_s=0.1,
        )
        before = _count("service.autoscale_adjustments")
        ctl.step()
        get_telemetry().metrics.histogram(INTERACTIVE_WAIT).observe(7.0)
        ctl.step()
        assert _count("service.autoscale_adjustments") > before
        events = _events("autoscale_adjustment")
        assert events
        latest = events[-1]
        assert {"knob", "from_value", "to_value", "reason", "at"} <= set(
            latest
        )

    def test_respects_worker_bounds(self):
        sched = _FakeScheduler(workers=3)
        ctl = AutoscaleController(
            sched, clock=ManualClock(), min_workers=3, max_workers=3,
            target_interactive_p99_s=0.1,
        )
        ctl.step()
        get_telemetry().metrics.histogram(INTERACTIVE_WAIT).observe(8.0)
        ctl.step()  # pressure, but already at max
        assert sched.workers == 3
        for _ in range(IDLE_ROUNDS_BEFORE_SCALE_DOWN + 1):
            ctl.step()  # idle, but already at min
        assert sched.workers == 3

    def test_live_service_runs_the_loop(self):
        svc = VerificationService(
            workers=1, execute=lambda t: _FakeResult(),
            preemption=True, autoscale=True,
        )
        svc.start()
        try:
            assert svc.autoscaler is not None
            assert svc.autoscaler._thread is not None
            assert svc.autoscaler._thread.is_alive()
        finally:
            svc.stop(drain=False, timeout=10)
        assert not svc.autoscaler._thread


# ---------------------------------------------------------------------------
# real engine: preempted-then-resumed == uninterrupted, bit for bit
# ---------------------------------------------------------------------------


def _fingerprint(result):
    return tuple(
        sorted(
            (str(analyzer), repr(getattr(metric, "value", metric)))
            for analyzer, metric in dict(result.metrics).items()
        )
    )


class TestRealEngineDifferential:
    ROWS = 200_000

    def _make_dataset(self):
        import pyarrow as pa

        from deequ_tpu.data import Dataset

        rng = np.random.default_rng(23)
        return Dataset.from_arrow(
            pa.table(
                {
                    "k1": rng.integers(
                        0, 1 << 40, self.ROWS, dtype=np.int64
                    ),
                    "v1": rng.normal(0, 1, self.ROWS).astype(
                        np.float32
                    ),
                }
            )
        )

    def _suite(self):
        from deequ_tpu import Check, CheckLevel

        return [
            Check(CheckLevel.ERROR, "preempt-diff")
            .is_complete("k1")
            .is_non_negative("k1")
            .is_complete("v1")
        ]

    def _interactive_suite(self):
        from deequ_tpu import Check, CheckLevel

        return [
            Check(CheckLevel.ERROR, "preempt-quick").is_complete("k1")
        ]

    def _request(self, factory, priority, key):
        return RunRequest(
            tenant="acme",
            checks=(
                self._suite()
                if priority == Priority.BATCH
                else self._interactive_suite()
            ),
            dataset_key=key,
            dataset_factory=factory,
            priority=priority,
        )

    def _run_differential(self, factory, journal_root, placer=None):
        """One uninterrupted reference run, then the same suite
        preempted mid-scan and resumed; returns both fingerprints and
        the preemption count observed for the second leg."""
        from deequ_tpu import config

        with config.configure(
            batch_size=4096, checkpoint_every_batches=1
        ):
            solo_svc = VerificationService(
                workers=1, isolated=False, preemption=True,
                journal_dir=str(journal_root / "solo"),
                placer=placer,
            ).start()
            try:
                solo = solo_svc.submit(
                    self._request(factory, Priority.BATCH, "diff/solo")
                )
                assert solo.wait(timeout=120)
                assert solo.status == RunState.DONE
            finally:
                solo_svc.stop(drain=False, timeout=30)

            before = _count("service.preemptions")
            svc = VerificationService(
                workers=1, isolated=False, preemption=True,
                journal_dir=str(journal_root / "preempted"),
                placer=placer,
            ).start()
            try:
                batch = svc.submit(
                    self._request(factory, Priority.BATCH, "diff/batch")
                )
                assert _spin_until(
                    lambda: batch.status == RunState.RUNNING,
                    timeout_s=60,
                )
                quick = svc.submit(
                    self._request(
                        factory, Priority.INTERACTIVE, "diff/quick"
                    )
                )
                assert quick.wait(timeout=120)
                assert batch.wait(timeout=120)
                assert batch.status == RunState.DONE
                result = batch.result(timeout=0)
                assert result.interruption is None
            finally:
                svc.stop(drain=False, timeout=30)
            preemptions = _count("service.preemptions") - before
            return (
                _fingerprint(solo.result(timeout=0)),
                _fingerprint(result),
                preemptions,
            )

    def test_resident_preempt_resume_bit_identical(self, tmp_path):
        solo_print, resumed_print, preemptions = self._run_differential(
            self._make_dataset, tmp_path
        )
        assert preemptions == 1
        assert _count("service.preempt_resumes") >= 1
        assert resumed_print == solo_print

    def test_streaming_preempt_resume_bit_identical(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu import config
        from deequ_tpu.data import Dataset

        rng = np.random.default_rng(29)
        table = pa.table(
            {
                "k1": rng.integers(
                    0, 1 << 40, self.ROWS, dtype=np.int64
                ),
                "v1": rng.normal(0, 1, self.ROWS).astype(np.float32),
            }
        )
        data_dir = tmp_path / "parquet"
        data_dir.mkdir()
        shard = self.ROWS // 4
        for i in range(4):
            pq.write_table(
                table.slice(i * shard, None if i == 3 else shard),
                str(data_dir / f"part{i}.parquet"),
            )

        def factory():
            return Dataset.from_parquet(str(data_dir))

        with config.configure(device_cache_bytes=0):
            solo_print, resumed_print, preemptions = (
                self._run_differential(factory, tmp_path)
            )
        assert preemptions == 1
        assert resumed_print == solo_print

    def test_mesh_placed_preempt_revokes_lease(self, tmp_path):
        """The placer-backed variant: the victim holds a device lease,
        so the preemption path must revoke it (accounted) rather than
        release it — and the resumed run must still be bit-equal."""
        from deequ_tpu.service import ElasticPlacer

        lease_revocations = _count("service.lease_revocations")
        solo_print, resumed_print, preemptions = self._run_differential(
            self._make_dataset, tmp_path, placer=ElasticPlacer()
        )
        assert preemptions == 1
        assert resumed_print == solo_print
        assert _count("service.lease_revocations") > lease_revocations


# ---------------------------------------------------------------------------
# spawn-path preemption: the isolated child exits cleanly, no SIGKILL
# ---------------------------------------------------------------------------


def _spawn_dataset():
    """Module-level (spawn pickles by reference): the child rebuilds
    the same deterministic table from the seed."""
    import pyarrow as pa

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(31)
    rows = 200_000
    return Dataset.from_arrow(
        pa.table(
            {
                "k1": rng.integers(0, 1 << 40, rows, dtype=np.int64),
                "v1": rng.normal(0, 1, rows).astype(np.float32),
            }
        )
    )


class TestIsolatedPreemption:
    def test_preempt_during_spawn_execution(self, tmp_path):
        """The victim runs in a spawn child: the preempt token's
        cancel crosses the control pipe, the child exits through its
        checkpoint path (clean exit code, partial result in-band), and
        the requeued run resumes to a complete, uninterrupted result
        — the child is never terminated or killed. Checks hold
        lambdas (they never pickle), so the spawn-safe request carries
        ``required_analyzers`` — the test asserts the run really
        crossed the process boundary (no inline fallback)."""
        from deequ_tpu import config
        from deequ_tpu.analyzers import Completeness, Mean, Size

        before = _count("service.preempt_requeues")
        fallbacks = _count("service.isolation_inline_fallbacks")
        with config.configure(
            batch_size=4096, checkpoint_every_batches=1
        ):
            svc = VerificationService(
                workers=1, isolated=True, preemption=True,
                journal_dir=str(tmp_path),
            ).start()
            try:
                batch = svc.submit(
                    RunRequest(
                        tenant="acme",
                        checks=(),
                        required_analyzers=[
                            Completeness("k1"),
                            Mean("v1"),
                        ],
                        dataset_key="spawn/batch",
                        dataset_factory=_spawn_dataset,
                        priority=Priority.BATCH,
                    )
                )
                assert _spin_until(
                    lambda: batch.status == RunState.RUNNING,
                    timeout_s=60,
                )
                quick = svc.submit(
                    RunRequest(
                        tenant="globex",
                        checks=(),
                        required_analyzers=[Size()],
                        dataset_key="spawn/quick",
                        dataset_factory=_spawn_dataset,
                        priority=Priority.INTERACTIVE,
                    )
                )
                assert quick.wait(timeout=300)
                assert quick.status == RunState.DONE
                assert batch.wait(timeout=300)
                assert batch.status == RunState.DONE
                assert batch.result(timeout=0).interruption is None
            finally:
                svc.stop(drain=False, timeout=30)
        assert _count("service.preempt_requeues") == before + 1
        # both runs really spawned: nothing fell back in-process
        assert _count(
            "service.isolation_inline_fallbacks"
        ) == fallbacks
