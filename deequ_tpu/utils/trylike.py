"""Scala-style ``Try`` values: failures are data, not control flow.

The reference wraps every metric value in ``Try[Value]`` so a failed
analyzer (missing column, empty state, cast error) produces a *failure
metric* and the run still completes (reference:
``src/main/scala/com/amazon/deequ/metrics/Metric.scala``; SURVEY.md §2.1,
§5.3). This module is the Python equivalent used throughout deequ_tpu.
"""

from __future__ import annotations

from typing import Callable, Generic, TypeVar

T = TypeVar("T")
U = TypeVar("U")


class Try(Generic[T]):
    """Either a ``Success(value)`` or a ``Failure(exception)``."""

    @property
    def is_success(self) -> bool:
        raise NotImplementedError

    @property
    def is_failure(self) -> bool:
        return not self.is_success

    def get(self) -> T:
        raise NotImplementedError

    def get_or_else(self, default: U) -> T | U:
        return self.get() if self.is_success else default

    @property
    def exception(self) -> BaseException | None:
        return None

    def map(self, fn: Callable[[T], U]) -> "Try[U]":
        raise NotImplementedError

    @staticmethod
    def of(fn: Callable[[], T]) -> "Try[T]":
        try:
            return Success(fn())
        except Exception as exc:  # noqa: BLE001 — failures-as-values by design
            return Failure(exc)


class Success(Try[T]):
    __slots__ = ("_value",)

    def __init__(self, value: T):
        self._value = value

    @property
    def is_success(self) -> bool:
        return True

    def get(self) -> T:
        return self._value

    def map(self, fn: Callable[[T], U]) -> Try[U]:
        return Try.of(lambda: fn(self._value))

    def __repr__(self) -> str:
        return f"Success({self._value!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Success) and other._value == self._value

    def __hash__(self) -> int:
        return hash(("Success", self._value))


class Failure(Try[T]):
    __slots__ = ("_exception",)

    def __init__(self, exception: BaseException):
        self._exception = exception

    @property
    def is_success(self) -> bool:
        return False

    def get(self) -> T:
        raise self._exception

    @property
    def exception(self) -> BaseException:
        return self._exception

    def map(self, fn: Callable[[T], U]) -> Try[U]:
        return Failure(self._exception)

    def __repr__(self) -> str:
        return f"Failure({self._exception!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Failure)
            and type(other._exception) is type(self._exception)
            and str(other._exception) == str(self._exception)
        )

    def __hash__(self) -> int:
        return hash(("Failure", type(self._exception), str(self._exception)))
