"""Render per-run trace waterfalls and critical-path decompositions
from a telemetry JSONL artifact.

Usage:

    python -m tools.trace_report runs.jsonl              # every trace
    python -m tools.trace_report runs.jsonl --run <id>   # one trace
    python -m tools.trace_report runs.jsonl --json       # machine form

The artifact is the ordinary telemetry JSONL (``configure(jsonl_path=
...)`` or per-host files concatenated); any span line carrying a
``trace_id`` participates. One trace = one submission's causal
timeline: the synthetic ``ticket`` root span (submit -> finished wall)
with queue_wait / coalesce_window / lease_wait / execute / engine
children — across processes, since spawn children stream their spans
back and replay re-roots them (docs/OBSERVABILITY.md "Tracing").

The critical-path decomposition attributes every span's SELF time
(wall minus children) to one of the fixed stages below, so the stage
seconds of a run sum to its root wall by construction — no stage
double-counts a nested child. A ``coalesced_scan`` link span (a
member's view of the host's superset scan) is resolved by descending
into the linked host subtree and apportioning the link's wall by the
host's own stage fractions.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Any, Dict, List, Optional, Tuple

from deequ_tpu.telemetry import read_jsonl

#: the fixed critical-path stages, in pipeline order
STAGES = (
    "queue_wait",
    "coalesce_window",
    "lease_wait",
    "compile",
    "scan",
    "finalize",
    "egress",
    "persist",
)

#: exact span-name -> stage attribution; names not listed fall through
#: to the prefix rules, then inherit their parent's stage
_STAGE_BY_NAME = {
    "queue_wait": "queue_wait",
    "coalesce_window": "coalesce_window",
    "lease_wait": "lease_wait",
    "phase:compile": "compile",
    "phase:scan": "scan",
    "egress": "egress",
    "persist": "persist",
    "ticket": "finalize",
    "execute": "finalize",
}


def _stage_for(name: str, parent_stage: str) -> str:
    stage = _STAGE_BY_NAME.get(name)
    if stage is not None:
        return stage
    if name.startswith("pass:") or name.startswith("phase:"):
        return "scan"
    if name.startswith("run:"):
        return "finalize"
    return parent_stage or "finalize"


def load_traces(
    records: List[Dict[str, Any]],
) -> Dict[str, List[Dict[str, Any]]]:
    """Span records grouped by trace_id, in file order (spans without a
    trace_id — untraced runs — are not part of any timeline)."""
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("type") != "span" or not r.get("trace_id"):
            continue
        traces.setdefault(str(r["trace_id"]), []).append(r)
    return traces


class _Tree:
    """Index of one trace's spans: children adjacency + the root."""

    def __init__(self, spans: List[Dict[str, Any]]):
        self.by_id: Dict[int, Dict[str, Any]] = {}
        for sp in spans:
            sid = sp.get("span_id")
            if isinstance(sid, int) and sid not in self.by_id:
                self.by_id[sid] = sp
        self.children: Dict[Optional[int], List[Dict[str, Any]]] = {}
        roots: List[Dict[str, Any]] = []
        for sp in self.by_id.values():
            parent = sp.get("parent_id")
            if parent in self.by_id and parent != sp.get("span_id"):
                self.children.setdefault(parent, []).append(sp)
            else:
                roots.append(sp)
        for kids in self.children.values():
            kids.sort(key=lambda s: s.get("started_at", 0.0))
        # the synthetic ticket root has parent None; tolerate torn
        # artifacts by falling back to the longest parentless span
        roots.sort(
            key=lambda s: (
                s.get("parent_id") is not None,
                -float(s.get("wall_s", 0.0)),
            )
        )
        self.root = roots[0] if roots else None
        self.orphans = roots[1:]

    def kids(self, sp: Dict[str, Any]) -> List[Dict[str, Any]]:
        return self.children.get(sp.get("span_id"), [])

    def self_s(self, sp: Dict[str, Any]) -> float:
        wall = float(sp.get("wall_s", 0.0))
        nested = sum(float(k.get("wall_s", 0.0)) for k in self.kids(sp))
        return max(0.0, wall - nested)


def _link_target(
    sp: Dict[str, Any], trees: Dict[str, "_Tree"]
) -> Optional[Tuple["_Tree", Dict[str, Any]]]:
    attrs = sp.get("attributes") or {}
    link_trace = attrs.get("link_trace_id")
    link_span = attrs.get("link_span_id")
    tree = trees.get(str(link_trace)) if link_trace else None
    if tree is None:
        return None
    target = tree.by_id.get(link_span)
    if target is None:
        return None
    return tree, target


def _accumulate(
    tree: _Tree,
    sp: Dict[str, Any],
    parent_stage: str,
    out: Dict[str, float],
    trees: Dict[str, _Tree],
) -> None:
    name = str(sp.get("name", ""))
    if name == "coalesced_scan":
        # a member's link onto the host's superset scan: apportion the
        # link's wall by the linked subtree's own stage fractions so
        # the member's timeline stays honest about WHERE the shared
        # wall went (all-scan when the host trace is not in the file)
        wall = float(sp.get("wall_s", 0.0))
        linked = _link_target(sp, trees)
        if linked is not None:
            host_tree, host_span = linked
            host_stages: Dict[str, float] = {}
            _accumulate(
                host_tree, host_span, "scan", host_stages, trees
            )
            total = sum(host_stages.values())
            if total > 0:
                for stage, value in host_stages.items():
                    out[stage] = (
                        out.get(stage, 0.0) + wall * value / total
                    )
                return
        out["scan"] = out.get("scan", 0.0) + wall
        return
    stage = _stage_for(name, parent_stage)
    out[stage] = out.get(stage, 0.0) + tree.self_s(sp)
    for kid in tree.kids(sp):
        _accumulate(tree, kid, stage, out, trees)


def decompose(
    trace_id: str, trees: Dict[str, _Tree]
) -> Dict[str, Any]:
    """One trace's critical-path stages: {stage: seconds} summing to
    the root wall, plus root metadata for reports."""
    tree = trees[trace_id]
    stages: Dict[str, float] = {}
    root = tree.root
    if root is None:
        return {"trace_id": trace_id, "wall_s": 0.0, "stages": {}}
    _accumulate(tree, root, "", stages, trees)
    for orphan in tree.orphans:
        _accumulate(tree, orphan, "", stages, trees)
    attrs = root.get("attributes") or {}
    return {
        "trace_id": trace_id,
        "run_id": attrs.get("run_id"),
        "tenant": attrs.get("tenant"),
        "status": attrs.get("status"),
        "wall_s": float(root.get("wall_s", 0.0)),
        "stages": {
            k: stages.get(k, 0.0)
            for k in STAGES
            if stages.get(k, 0.0) > 0.0
        },
    }


def dominant_stage(stages: Dict[str, float]) -> Tuple[str, float]:
    if not stages:
        return "finalize", 0.0
    name = max(stages, key=lambda k: stages[k])
    total = sum(stages.values())
    return name, (stages[name] / total if total > 0 else 0.0)


def _quantile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
    return ordered[idx]


def aggregate(decomps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet view across runs: p50/p99 wall, each attributed to the
    dominant stage of the run AT that quantile — the stage a capacity
    fix should target first."""
    walls = [d["wall_s"] for d in decomps]
    out: Dict[str, Any] = {"runs": len(decomps)}
    for label, q in (("p50", 0.5), ("p99", 0.99)):
        wall = _quantile(walls, q)
        at = min(
            decomps, key=lambda d: (abs(d["wall_s"] - wall), d["trace_id"])
        )
        stage, share = dominant_stage(at["stages"])
        out[label] = {
            "wall_s": wall,
            "dominant_stage": stage,
            "dominant_share": share,
        }
    out["stage_p50_s"] = {
        stage: _quantile([d["stages"].get(stage, 0.0) for d in decomps], 0.5)
        for stage in STAGES
    }
    out["stage_p99_s"] = {
        stage: _quantile([d["stages"].get(stage, 0.0) for d in decomps], 0.99)
        for stage in STAGES
    }
    return out


# -- rendering -------------------------------------------------------------


def _render_span(
    tree: _Tree,
    sp: Dict[str, Any],
    t0: float,
    depth: int,
    lines: List[str],
) -> None:
    offset = float(sp.get("started_at", t0)) - t0
    name = str(sp.get("name", "?"))
    process = sp.get("process")
    suffix = f"  [{process}]" if process else ""
    attrs = sp.get("attributes") or {}
    link = (
        f"  -> {attrs.get('link_trace_id')}"
        if name == "coalesced_scan" and attrs.get("link_trace_id")
        else ""
    )
    lines.append(
        f"  {'  ' * depth}{max(0.0, offset):8.3f}s "
        f"+{float(sp.get('wall_s', 0.0)):.3f}s  {name}{link}{suffix}"
    )
    for kid in tree.kids(sp):
        _render_span(tree, kid, t0, depth + 1, lines)


def render_trace(
    trace_id: str, trees: Dict[str, _Tree]
) -> str:
    tree = trees[trace_id]
    if tree.root is None:
        return f"trace {trace_id}: no spans"
    root = tree.root
    attrs = root.get("attributes") or {}
    head = f"trace {trace_id}"
    if attrs.get("run_id"):
        head += f"  run={attrs['run_id']}"
    if attrs.get("tenant"):
        head += f"  tenant={attrs['tenant']}"
    if attrs.get("status"):
        head += f"  status={attrs['status']}"
    lines = [head]
    t0 = float(root.get("started_at", 0.0))
    _render_span(tree, root, t0, 0, lines)
    for orphan in tree.orphans:
        _render_span(tree, orphan, t0, 0, lines)
    d = decompose(trace_id, trees)
    wall = d["wall_s"]
    covered = sum(d["stages"].values())
    lines.append(
        f"  critical path ({wall:.3f}s wall,"
        f" {100.0 * covered / wall if wall > 0 else 0.0:.0f}% attributed):"
    )
    for stage in STAGES:
        value = d["stages"].get(stage, 0.0)
        if value <= 0.0:
            continue
        share = 100.0 * value / wall if wall > 0 else 0.0
        lines.append(f"    {stage:<16} {value:9.3f}s  {share:5.1f}%")
    return "\n".join(lines)


def render_aggregate(decomps: List[Dict[str, Any]]) -> str:
    agg = aggregate(decomps)
    lines = [f"aggregate over {agg['runs']} traced run(s):"]
    for label in ("p50", "p99"):
        stat = agg[label]
        lines.append(
            f"  {label} wall {stat['wall_s']:.3f}s — dominant stage:"
            f" {stat['dominant_stage']}"
            f" ({100.0 * stat['dominant_share']:.0f}% of that run)"
        )
    lines.append(f"  {'stage':<16} {'p50':>9} {'p99':>9}")
    for stage in STAGES:
        p50 = agg["stage_p50_s"].get(stage, 0.0)
        p99 = agg["stage_p99_s"].get(stage, 0.0)
        if p50 <= 0.0 and p99 <= 0.0:
            continue
        lines.append(f"  {stage:<16} {p50:8.3f}s {p99:8.3f}s")
    return "\n".join(lines)


def _match(trace_id: str, tree: _Tree, wanted: str) -> bool:
    if trace_id == wanted or trace_id.startswith(wanted):
        return True
    root = tree.root
    if root is None:
        return False
    attrs = root.get("attributes") or {}
    return str(attrs.get("run_id", "")) == wanted


def render(
    records: List[Dict[str, Any]],
    run: Optional[str] = None,
    as_json: bool = False,
) -> str:
    traces = load_traces(records)
    trees = {tid: _Tree(spans) for tid, spans in traces.items()}
    selected = [
        tid
        for tid, tree in trees.items()
        if run is None or _match(tid, tree, run)
    ]
    if not selected:
        if run is not None:
            return f"no trace matching {run!r} in artifact"
        n_spans = sum(1 for r in records if r.get("type") == "span")
        return (
            f"no traced spans in artifact ({n_spans} untraced span(s))"
            " — was the service started with service_trace enabled?"
        )
    decomps = [decompose(tid, trees) for tid in selected]
    if as_json:
        payload: Dict[str, Any] = {"runs": decomps}
        if len(decomps) > 1:
            payload["aggregate"] = aggregate(decomps)
        return json.dumps(payload, indent=2, sort_keys=True)
    body = "\n\n".join(render_trace(tid, trees) for tid in selected)
    if len(decomps) > 1:
        body += "\n\n" + render_aggregate(decomps)
    return body


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Render trace waterfalls and critical-path "
        "decompositions from a telemetry JSONL artifact"
    )
    parser.add_argument("path", help="telemetry JSONL file")
    parser.add_argument(
        "--run",
        default=None,
        help="render only the trace matching this trace_id (prefix) "
        "or submission run_id",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit machine-readable JSON"
    )
    args = parser.parse_args(argv)
    try:
        records = read_jsonl(args.path)
    except OSError as exc:
        print(f"cannot read {args.path}: {exc}", file=sys.stderr)
        return 2
    print(render(records, run=args.run, as_json=args.json))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
