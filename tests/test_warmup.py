"""tools/warmup.py: schema-driven synthetic data must hit the same
static compile decisions as production data (kinds, wire dtypes,
nullability) so precompiled plans actually get reused."""

import numpy as np
import pyarrow as pa

from deequ_tpu import config
from deequ_tpu.profiles.profiler import ColumnProfiler

from tools.warmup import _schema_from_parquet, synthetic_dataset, warm_once


SCHEMA = {
    "f": "float32",
    "d": "float64",
    "i": "int64",
    "s": "string",
    "b": "bool",
    "t": "timestamp",
}


def test_synthetic_dataset_matches_schema_kinds():
    ds = synthetic_dataset(SCHEMA, 1000, nullable=True, wide_ints=True)
    # high-card strings widen the code dtype (a distinct program)
    wide_s = synthetic_dataset(
        SCHEMA, 1000, nullable=False, wide_ints=False,
        high_card_strings=True,
    )
    from deequ_tpu.data.table import ColumnRequest as _CR

    assert wide_s.materialize(_CR("s", "codes")).dtype == np.int16
    kinds = {f.name: f.kind.name for f in ds.schema.fields}
    assert kinds == {
        "f": "FRACTIONAL",
        "d": "FRACTIONAL",
        "i": "INTEGRAL",
        "s": "STRING",
        "b": "BOOLEAN",
        "t": "TIMESTAMP",
    }
    # nullable=True must produce real masks (compiles differ)
    assert ds.table.column("f").null_count > 0
    # wide ints must NOT narrow to i32 (a narrowed program differs)
    from deequ_tpu.data.table import ColumnRequest

    assert ds.materialize(ColumnRequest("i", "values")).dtype == np.int64
    narrow = synthetic_dataset(SCHEMA, 1000, nullable=False, wide_ints=False)
    assert (
        narrow.materialize(ColumnRequest("i", "values")).dtype == np.int32
    )


def test_warm_once_runs_and_plan_is_reused():
    schema = {"x": "float32", "s": "string"}
    with config.configure(batch_size=512):
        warm_once(schema, 512, nullable=False, wide_ints=False, suite=False)
        # a fresh same-schema dataset reuses the in-process plan cache
        from deequ_tpu.engine.scan import AnalysisEngine

        engine = AnalysisEngine(batch_size=512)
        ds = synthetic_dataset(schema, 512, False, False, seed=7)
        ColumnProfiler.profile(ds, engine=engine)
        assert engine.plan_cache_hit or engine.trace_count == 0


def test_schema_from_parquet(tmp_path):
    import pyarrow.parquet as pq

    tbl = pa.table(
        {
            "a": pa.array([1.5], pa.float32()),
            "b": pa.array([1], pa.int64()),
            "c": pa.array(["x"]).dictionary_encode(),
        }
    )
    pq.write_table(tbl, str(tmp_path / "t.parquet"))
    assert _schema_from_parquet(str(tmp_path / "t.parquet")) == {
        "a": "float32",
        "b": "int64",
        "c": "string",
    }
