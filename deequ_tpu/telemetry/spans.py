"""Span tracer: nested, attribute-carrying spans with thread-local
context.

Each finished span carries (name, span_id, parent_id, thread, wall_s,
attributes); nesting is tracked per-thread, so concurrent runs (or the
engine's prefetch worker) can never corrupt each other's parentage.
When annotation is on and jax is importable, every span also emits a
``jax.profiler.TraceAnnotation`` under the SAME ``deequ_tpu:<name>``
label — an XProf/TensorBoard trace and the in-repo timings share names,
so a kernel-level investigation and a span report line up 1:1.

The clock helpers here are the ONE sanctioned home of
``time.perf_counter`` — hot-path modules must route timing through this
layer (enforced by tools/telemetry_lint.py).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, Optional

_span_ids = itertools.count(1)


def clock() -> float:
    """Monotonic seconds — the sanctioned timing source for callers
    outside the telemetry layer (see tools/telemetry_lint.py)."""
    return time.perf_counter()


@dataclass
class Span:
    name: str
    span_id: int
    parent_id: Optional[int]
    thread: str
    started_at: float  # epoch seconds (export ordering across threads)
    wall_s: float = 0.0
    attributes: Dict[str, Any] = field(default_factory=dict)

    def set(self, **attrs: Any) -> "Span":
        self.attributes.update(attrs)
        return self

    def as_record(self) -> Dict[str, Any]:
        return {
            "type": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread,
            "started_at": round(self.started_at, 6),
            "wall_s": round(self.wall_s, 6),
            "attributes": dict(self.attributes),
        }


class _NoopSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()
    wall_s = 0.0

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self


NOOP_SPAN = _NoopSpan()
# reusable: nullcontext always returns its enter_result, so ONE instance
# serves every disabled span() call with zero allocation
NOOP_SPAN_CM = contextlib.nullcontext(NOOP_SPAN)


def _trace_annotation(name: str):
    """A jax TraceAnnotation for ``name``, or None when jax is absent
    (telemetry stays importable without an accelerator stack)."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(f"deequ_tpu:{name}")
    except Exception:  # noqa: BLE001 — annotation is best-effort
        return None


class Tracer:
    """Thread-safe span context. Each thread owns its span stack; the
    finished-span callback is invoked on the finishing thread."""

    def __init__(self, annotate: bool = True):
        self.annotate = annotate
        self._local = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        on_finish: Optional[Callable[[Span], None]] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        stack = self._stack()
        sp = Span(
            name=name,
            span_id=next(_span_ids),
            parent_id=stack[-1].span_id if stack else None,
            thread=threading.current_thread().name,
            started_at=time.time(),
            attributes=dict(attributes),
        )
        stack.append(sp)
        annotation = _trace_annotation(name) if self.annotate else None
        t0 = time.perf_counter()
        try:
            if annotation is None:
                yield sp
            else:
                with annotation:
                    yield sp
        finally:
            sp.wall_s = time.perf_counter() - t0
            # pop by identity: an exception while a child span is still
            # open must not mis-pop the parent
            if stack and stack[-1] is sp:
                stack.pop()
            elif sp in stack:
                stack.remove(sp)
            if on_finish is not None:
                on_finish(sp)


@contextlib.contextmanager
def profiler_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace of the wrapped block into
    ``log_dir`` (open with TensorBoard's profile plugin / XProf).
    Span TraceAnnotations emitted inside the block appear in the dump
    under their ``deequ_tpu:<name>`` labels."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
