"""Fleet failover: heartbeat leases, orphan adoption, epoch fencing.

Every robustness layer below this one protects a single process — the
watchdog, crash isolation, the durable run journal, preemption, durable
egress. This module is the fleet-level composition (docs/SERVICE.md
"Fleet failover"): N service replicas share a *fleet directory* (any
``io/storage.py`` backend), each holding a durable, epoch-numbered
heartbeat lease there. A :class:`FleetSupervisor` renews its own lease
on the injected service clock and watches every peer's; when a peer's
lease goes stale the survivor ADOPTS the dead replica's journal
directory — claims the orphan's lease chain under a new epoch with a
compare-and-swap (exactly one adopter can win) and replays its
``pending_runs()`` through the service's recover path, so started runs
resume from their durable ``ScanCursor``s with zero recompute.

The lease chain, concretely: replica ``r``'s lease at epoch ``E`` is
the blob ``leases/lease-{r}-{E:08d}.json`` — a dedicated subdirectory,
so chain reads never pay for sibling trees like the shared checkpoint
dir. Claiming epoch ``E+1`` is a CAS-create of the next file in the
chain (expected = absent) — never an overwrite of the current one — so
a slow heartbeat can never clobber an adoption. Heartbeats are plain
durable overwrites of the OWN epoch file bumping a ``stamp`` counter;
expiry is judged by how long a peer's ``(epoch, stamp)`` pair has sat
unchanged on the watcher's OWN clock, so no cross-host clock
comparison ever happens.

Adoption is write-ahead like everything else durable here: before the
claim CAS, ``on_adopt_intent`` durably records the adoption intent
(orphan chain + journal dir + claim epoch) in the ADOPTER's own
journal. A claim alone is a terminal state nobody re-polls — so if the
adopter dies between winning the CAS and journaling the orphan's runs,
whoever adopts the ADOPTER's chain finds the unfinished intent and
completes the adoption (service ``_finish_adoption``), and a
``recover()`` of the same journal does the same. No run is ever
stranded behind a half-done claim.

Epoch fencing: a zombie — a replica revived after a GC pause or
network partition during which a peer adopted it — discovers on its
next fence check that its chain has a higher epoch it does not own,
and must drop every journal/repository/manifest write from then on
(the adopter owns those runs now). :func:`epoch_fence_check` is that
guard; the ``fence-discipline`` staticcheck rule requires it lexically
before every persist call in ``deequ_tpu/service/``, and
``engine/subproc.py`` ships the epoch to child processes so a child of
a fenced parent also stops persisting.

Poison quarantine: a run that crash-loops is circuit-broken per
process by ``engine/subproc.py``'s breaker — but a poison run adopted
fleet-wide would crash every replica in turn. The supervisor keeps a
shared breaker ledger (``poison-*.json``) of which DISTINCT replicas a
plan key has crashed; at ``poison_replicas`` distinct victims the key
is quarantined fleet-wide and adoption refuses to re-admit it.
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from deequ_tpu.engine.deadline import MonotonicClock
from deequ_tpu.io.storage import Storage, compare_and_swap, storage_for
from deequ_tpu.telemetry import get_telemetry

#: leases live in their own subdirectory so chain listings walk ONLY
#: lease files — the fleet dir also hosts ``checkpoints/``, whose file
#: count grows with every run, and fence checks sit on persist paths.
#: ``engine/subproc.py child_epoch_fenced`` mirrors this layout.
LEASE_DIR = "leases"
LEASE_PREFIX = "lease-"
POISON_PREFIX = "poison-"

#: lease lifecycle states. ``live`` — heartbeating owner; ``adopted`` —
#: a survivor claimed this chain (terminal: the chain names a dead
#: replica whose runs moved to the adopter's journal); ``retired`` —
#: the owner stopped cleanly, nothing to adopt.
LEASE_STATES = ("live", "adopted", "retired")


class FencedReplica(RuntimeError):
    """This replica's lease epoch has been superseded by an adopter:
    it must not accept, execute, or persist anything. Raised by the
    service's admission path; persist paths silently drop instead
    (the write's rightful owner is the adopter)."""


@dataclass
class Lease:
    """One parsed lease blob — the newest epoch of one replica chain."""

    replica: str
    epoch: int
    stamp: int
    owner: str
    journal_dir: str
    state: str = "live"

    def body(self) -> bytes:
        return json.dumps(
            {
                "replica": self.replica,
                "epoch": self.epoch,
                "stamp": self.stamp,
                "owner": self.owner,
                "journal_dir": self.journal_dir,
                "state": self.state,
            },
            sort_keys=True,
        ).encode()


@dataclass
class FleetAdoption:
    """What :meth:`FleetSupervisor.poll` hands the adoption callback
    after winning a lease CAS: the orphan chain's identity and journal
    directory, plus how long the lease had been stale on the
    adopter's clock when it was claimed."""

    replica: str
    epoch: int
    journal_dir: str
    stale_for_s: float


def _lease_key(replica: str, epoch: int) -> str:
    return f"{LEASE_DIR}/{LEASE_PREFIX}{replica}-{epoch:08d}.json"


def _chain_prefix(replica: str = "") -> str:
    return f"{LEASE_DIR}/{LEASE_PREFIX}{replica}{'-' if replica else ''}"


def _parse_lease(raw: Optional[bytes]) -> Optional[Lease]:
    if raw is None:
        return None
    try:
        body = json.loads(raw)
        return Lease(
            replica=str(body["replica"]),
            epoch=int(body["epoch"]),
            stamp=int(body.get("stamp", 0)),
            owner=str(body.get("owner", body["replica"])),
            journal_dir=str(body.get("journal_dir", "")),
            state=str(body.get("state", "live")),
        )
    except Exception:  # noqa: BLE001 — torn/foreign blob = no lease
        return None


def _poison_key(plan_key: str) -> str:
    digest = hashlib.sha256(plan_key.encode()).hexdigest()[:16]
    return f"{POISON_PREFIX}{digest}.json"


class FleetSupervisor:
    """One replica's membership in the fleet: owns this replica's
    lease chain, watches every peer chain, and adopts expired ones.

    Timing discipline matches the rest of ``service/``: ages are
    measured on the INJECTED clock only (``MonotonicClock`` in
    production, ``ManualClock`` in tests — drive :meth:`heartbeat` /
    :meth:`poll` by hand); the optional background thread paces
    itself on a ``threading.Event`` wait, never ``time.sleep``.

    Not constructed directly in production — ``VerificationService``
    builds one when ``fleet_dir`` is configured and wires
    :meth:`poll`'s adoption callback into its recover path.
    """

    def __init__(
        self,
        fleet_dir: str,
        replica_id: str,
        journal_dir: str,
        *,
        clock: Optional[Any] = None,
        heartbeat_s: float = 2.0,
        lease_timeout_s: float = 10.0,
        poison_replicas: int = 2,
        on_adopt: Optional[Callable[[FleetAdoption], Any]] = None,
        on_adopt_intent: Optional[Callable[[FleetAdoption], Any]] = None,
        on_adopt_lost: Optional[Callable[[FleetAdoption], Any]] = None,
    ):
        if not replica_id:
            raise ValueError("replica_id must be non-empty")
        self.fleet_dir = fleet_dir
        self.replica_id = replica_id
        self.journal_dir = journal_dir
        self.heartbeat_s = float(heartbeat_s)
        self.lease_timeout_s = float(lease_timeout_s)
        self.poison_replicas = int(poison_replicas)
        self.on_adopt = on_adopt
        #: fired BEFORE the claim CAS: the service durably records the
        #: adoption intent in its journal; raising here ABORTS the
        #: claim (no durable intent -> no claim -> no run-loss window)
        self.on_adopt_intent = on_adopt_intent
        #: fired after a LOST claim CAS: the service marks the intent
        #: done so a later adopter does not replay a race it lost
        self.on_adopt_lost = on_adopt_lost
        self._clock = clock or MonotonicClock()
        self._storage: Storage = storage_for(fleet_dir)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.epoch = 0
        self._stamp = 0
        self._fenced = False
        #: local-clock time of the last chain read that confirmed this
        #: replica still owns its epoch — ``fenced()`` serves the
        #: unfenced verdict from this cache for up to one heartbeat
        #: interval, so per-persist fence checks cost no storage reads
        self._fence_ok_at: Optional[float] = None
        #: claims handed back by ``release_claim`` (fenced between the
        #: CAS win and the replay): ``_try_adopt`` must not record them
        self._released: set = set()
        #: chain -> ((epoch, stamp), local clock time last CHANGED) —
        #: staleness is judged against this, never a peer's clock
        self._peer_seen: Dict[str, Any] = {}
        self._adoptions: List[FleetAdoption] = []
        self._races_lost = 0
        self._register()

    # -- own lease ------------------------------------------------------

    def _chain_top(self, replica: str) -> Optional[Lease]:
        """The newest-epoch lease of one chain (file names sort by
        epoch, so the last key is the top)."""
        keys = self._storage.list_keys(_chain_prefix(replica))
        for key in reversed(keys):
            lease = _parse_lease(self._storage.read_bytes(key))
            # the prefix also matches chains whose id merely STARTS
            # with ours ("a" vs "a-b"); trust the blob, not the key
            if lease is not None and lease.replica == replica:
                return lease
        return None

    def _register(self) -> None:
        """Claim this replica's chain at (top epoch + 1). CAS-create so
        a zombie twin re-registering concurrently cannot silently share
        an epoch; bounded retries re-scan on each loss."""
        tm = get_telemetry()
        for _ in range(16):
            top = self._chain_top(self.replica_id)
            next_epoch = (top.epoch if top is not None else 0) + 1
            lease = Lease(
                replica=self.replica_id,
                epoch=next_epoch,
                stamp=0,
                owner=self.replica_id,
                journal_dir=self.journal_dir,
                state="live",
            )
            if compare_and_swap(
                self.fleet_dir,
                _lease_key(self.replica_id, next_epoch),
                None,
                lease.body(),
            ):
                with self._lock:
                    self.epoch = next_epoch
                    self._stamp = 0
                    self._fenced = False
                    self._fence_ok_at = self._clock.now()
                self._gc_chain(self.replica_id, keep_epoch=next_epoch)
                tm.metrics.gauge("service.fleet.lease_epoch").set(next_epoch)
                tm.event(
                    "fleet_lease_claimed",
                    replica=self.replica_id,
                    epoch=next_epoch,
                    journal_dir=self.journal_dir,
                )
                return
        raise RuntimeError(
            f"could not claim a lease epoch for {self.replica_id!r} "
            f"in {self.fleet_dir!r} (16 CAS losses — is another "
            "process registering under the same replica id in a "
            "tight loop?)"
        )

    def heartbeat(self) -> bool:
        """Renew the own lease (durable stamp bump) — unless the chain
        has moved past our epoch, in which case we are fenced: return
        False and renew nothing. Safe as a plain overwrite because
        only the epoch's owner ever writes an existing lease file;
        every other actor CAS-creates the NEXT epoch."""
        tm = get_telemetry()
        top = self._chain_top(self.replica_id)
        with self._lock:
            if top is None or top.epoch > self.epoch or (
                top.epoch == self.epoch and top.owner != self.replica_id
            ):
                self._fenced = True
            if self._fenced:
                return False
            self._fence_ok_at = self._clock.now()
            self._stamp += 1
            lease = Lease(
                replica=self.replica_id,
                epoch=self.epoch,
                stamp=self._stamp,
                owner=self.replica_id,
                journal_dir=self.journal_dir,
                state="live",
            )
        self._storage.write_bytes(
            _lease_key(self.replica_id, lease.epoch),
            lease.body(),
            durable=True,
        )
        tm.counter("service.fleet.heartbeats").inc()
        return True

    def fenced(self) -> bool:
        """Re-check ownership of the own chain. Sticky: once fenced,
        always fenced — a superseded epoch is never reclaimed; the
        process must restart to re-register. The UNFENCED verdict is
        cached for one heartbeat interval on the injected clock (every
        heartbeat refreshes it with a real chain read), so the fence
        checks on persist paths — submit, checkpoint saves, terminal
        records — cost no storage listing; the zombie window this
        staleness admits is at most one heartbeat, the same cadence
        the background loop re-checks at anyway."""
        now = self._clock.now()
        with self._lock:
            if self._fenced:
                return True
            if (
                self._fence_ok_at is not None
                and (now - self._fence_ok_at) < self.heartbeat_s
            ):
                return False
            my_epoch = self.epoch
        top = self._chain_top(self.replica_id)
        fenced_now = top is None or top.epoch > my_epoch or (
            top.epoch == my_epoch and top.owner != self.replica_id
        )
        with self._lock:
            if fenced_now:
                self._fenced = True
            else:
                self._fence_ok_at = now
        return fenced_now

    def retire(self) -> None:
        """Clean-stop marker: flip the own lease to ``retired`` so
        peers skip the chain instead of adopting an empty journal
        after the timeout. A fenced replica writes nothing."""
        with self._lock:
            if self._fenced:
                return
            lease = Lease(
                replica=self.replica_id,
                epoch=self.epoch,
                stamp=self._stamp,
                owner=self.replica_id,
                journal_dir=self.journal_dir,
                state="retired",
            )
        self._storage.write_bytes(
            _lease_key(self.replica_id, lease.epoch),
            lease.body(),
            durable=True,
        )
        get_telemetry().event(
            "fleet_lease_retired",
            replica=self.replica_id,
            epoch=lease.epoch,
        )

    # -- peer watch + adoption -----------------------------------------

    def _chains(self) -> Dict[str, Lease]:
        """chain id -> top lease, for every chain in the fleet dir."""
        tops: Dict[str, Lease] = {}
        for key in self._storage.list_keys(_chain_prefix()):
            lease = _parse_lease(self._storage.read_bytes(key))
            if lease is None:
                continue
            prev = tops.get(lease.replica)
            if prev is None or lease.epoch > prev.epoch:
                tops[lease.replica] = lease
        return tops

    def poll(self) -> List[FleetAdoption]:
        """One watch cycle: refresh peer staleness clocks, adopt every
        chain whose lease sat unchanged past ``lease_timeout_s``.
        Returns the adoptions won THIS call (callbacks already fired).
        Driven by the background thread in production, by hand in
        tests and single-shot tools. A fenced replica never watches or
        adopts: its own runs belong to its adopter, and a zombie
        winning an adoption CAS only to stand down at the service's
        fence check would strand the orphan's runs."""
        with self._lock:
            if self._fenced:
                return []
        tm = get_telemetry()
        now = self._clock.now()
        adopted: List[FleetAdoption] = []
        chains = self._chains()
        tm.metrics.gauge("service.fleet.peers").set(
            sum(
                1
                for c in chains.values()
                if c.replica != self.replica_id and c.state == "live"
            )
        )
        for chain_id, lease in chains.items():
            if chain_id == self.replica_id:
                continue
            if lease.state in ("retired", "adopted"):
                self._peer_seen.pop(chain_id, None)
                continue
            mark = (lease.epoch, lease.stamp)
            seen = self._peer_seen.get(chain_id)
            if seen is None or seen[0] != mark:
                self._peer_seen[chain_id] = (mark, now)
                continue
            stale_for = now - seen[1]
            if stale_for <= self.lease_timeout_s:
                continue
            tm.event(
                "fleet_lease_expired",
                replica=chain_id,
                epoch=lease.epoch,
                stale_for_s=round(stale_for, 3),
                observer=self.replica_id,
            )
            adoption = self._try_adopt(lease, stale_for)
            if adoption is not None:
                adopted.append(adoption)
        return adopted

    def _try_adopt(
        self, lease: Lease, stale_for_s: float
    ) -> Optional[FleetAdoption]:
        """Claim a dead chain at (epoch + 1). The CAS-create is the
        exactly-one-adopter guarantee: every racing survivor computes
        the same next key, and the storage backend admits one write.

        Write-ahead ordering: the ``on_adopt_intent`` callback lands a
        durable adoption-intent record in the adopter's journal BEFORE
        the CAS — an intent that fails aborts the claim (better to
        lose the race than hold a claim no crash can recover), and a
        claim whose replay never finishes is completed by whoever
        adopts the adopter (the intent names the orphan journal)."""
        if self.fenced():
            return None
        tm = get_telemetry()
        claim = Lease(
            replica=lease.replica,
            epoch=lease.epoch + 1,
            stamp=0,
            owner=self.replica_id,
            journal_dir=lease.journal_dir,
            state="adopted",
        )
        adoption = FleetAdoption(
            replica=lease.replica,
            epoch=claim.epoch,
            journal_dir=lease.journal_dir,
            stale_for_s=stale_for_s,
        )
        if self.on_adopt_intent is not None:
            try:
                self.on_adopt_intent(adoption)
            except Exception:  # noqa: BLE001 — no durable intent,
                tm.counter(  # no claim: the run-loss window stays shut
                    "service.fleet.adoption_intent_failures"
                ).inc()
                tm.event(
                    "fleet_adoption_intent_failed",
                    replica=lease.replica,
                    epoch=claim.epoch,
                    adopter=self.replica_id,
                )
                return None
        won = compare_and_swap(
            self.fleet_dir,
            _lease_key(lease.replica, claim.epoch),
            None,
            claim.body(),
        )
        if not won:
            self._races_lost += 1
            self._peer_seen.pop(lease.replica, None)
            tm.counter("service.fleet.adoption_races_lost").inc()
            tm.event(
                "fleet_adoption_race_lost",
                replica=lease.replica,
                epoch=claim.epoch,
                loser=self.replica_id,
            )
            if self.on_adopt_lost is not None:
                self.on_adopt_lost(adoption)
            return None
        self._peer_seen.pop(lease.replica, None)
        if self.on_adopt is not None:
            self.on_adopt(adoption)
        with self._lock:
            if (lease.replica, claim.epoch) in self._released:
                # the service handed the claim back (fenced between
                # the CAS win and the replay): the chain's previous
                # epoch is the top again, still adoptable — record
                # nothing, GC nothing
                self._released.discard((lease.replica, claim.epoch))
                return None
            self._adoptions.append(adoption)
        self._gc_chain(lease.replica, keep_epoch=claim.epoch)
        tm.counter("service.fleet.adoptions").inc()
        tm.event(
            "fleet_adoption",
            replica=lease.replica,
            epoch=claim.epoch,
            adopter=self.replica_id,
            journal_dir=lease.journal_dir,
            stale_for_s=round(stale_for_s, 3),
        )
        return adoption

    def adopt_chain(
        self, replica: str, journal_dir: str, stale_for_s: float = 0.0
    ) -> Optional[FleetAdoption]:
        """Claim ``replica``'s chain at its next epoch REGARDLESS of
        lease state — the finish-an-incomplete-adoption path (service
        ``_finish_adoption``): a dead adopter's journaled intent names
        a chain whose top is terminally ``adopted``, which ``poll``
        rightly skips forever; finishing it means claiming the NEXT
        epoch (the CAS keeps finishers unique) and replaying the
        orphan journal again — already-adopted runs are terminal
        there, so only the stranded ones re-admit."""
        if replica == self.replica_id:
            return None
        top = self._chain_top(replica)
        lease = (
            top
            if top is not None
            else Lease(
                replica=replica,
                epoch=0,
                stamp=0,
                owner=replica,
                journal_dir=journal_dir,
            )
        )
        if not lease.journal_dir:
            lease.journal_dir = journal_dir
        return self._try_adopt(lease, stale_for_s)

    def release_claim(self, replica: str, epoch: int) -> None:
        """Hand back a claim this replica just won: the service calls
        this when it finds itself fenced between the CAS win and the
        replay — standing down while HOLDING the claim would strand
        the orphan's runs forever (nothing re-polls an adopted chain).
        Deleting the claim blob is safe exactly here: the CAS win made
        this replica the blob's unique owner, and the chain GC has not
        run yet, so the previous (stale, live) epoch becomes the top
        again and a live survivor adopts it."""
        self._storage.delete(_lease_key(replica, epoch))
        with self._lock:
            self._released.add((replica, epoch))
        tm = get_telemetry()
        tm.counter("service.fleet.claims_released").inc()
        tm.event(
            "fleet_claim_released",
            replica=replica,
            epoch=epoch,
            holder=self.replica_id,
        )

    def _gc_chain(self, replica: str, keep_epoch: int) -> None:
        """Drop superseded lease files of one chain (satellite: cap
        fleet-dir growth — without this every heartbeat epoch bump and
        adoption leaves a file behind forever)."""
        removed = 0
        for key in self._storage.list_keys(_chain_prefix(replica)):
            lease = _parse_lease(self._storage.read_bytes(key))
            if (
                lease is not None
                and lease.replica == replica
                and lease.epoch < keep_epoch
            ):
                self._storage.delete(key)
                removed += 1
        if removed:
            get_telemetry().counter("service.fleet.lease_gc").inc(removed)

    # -- fleet poison ledger -------------------------------------------

    def note_crash_loop(self, plan_key: str) -> int:
        """Record that ``plan_key`` crash-looped THIS replica in the
        shared breaker ledger; returns the distinct-replica count. The
        per-process ``CircuitBreaker`` already stops local relaunches —
        this composes it across hosts so an adopted poison run cannot
        walk the fleet."""
        key = _poison_key(plan_key)
        for _ in range(16):
            raw = self._storage.read_bytes(key)
            try:
                body = json.loads(raw) if raw is not None else {}
            except Exception:  # noqa: BLE001 — torn ledger: rewrite
                body = {}
            replicas = sorted(
                set(body.get("replicas", [])) | {self.replica_id}
            )
            new = json.dumps(
                {"key": plan_key, "replicas": replicas}, sort_keys=True
            ).encode()
            if compare_and_swap(self.fleet_dir, key, raw, new):
                get_telemetry().event(
                    "fleet_crash_noted",
                    plan_key=plan_key,
                    replicas=replicas,
                )
                return len(replicas)
        return len(self.crashed_replicas(plan_key))

    def crashed_replicas(self, plan_key: str) -> List[str]:
        raw = self._storage.read_bytes(_poison_key(plan_key))
        try:
            body = json.loads(raw) if raw is not None else {}
        except Exception:  # noqa: BLE001
            body = {}
        return sorted(set(body.get("replicas", [])))

    def quarantined(self, plan_key: str) -> bool:
        """True once the key has crashed ``poison_replicas`` DISTINCT
        replicas — the fleet-level analog of an open breaker."""
        return len(self.crashed_replicas(plan_key)) >= self.poison_replicas

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        if self._thread is not None:
            return self
        self._stop.clear()
        # lint-ok: thread-discipline: fleet-scoped heartbeat/watch loop
        # owned by stop(); paced on Event.wait (injected-clock ages),
        # never part of a scan
        self._thread = threading.Thread(
            target=self._loop,
            daemon=True,
            name=f"deequ-tpu-fleet-{self.replica_id}",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if not self.heartbeat():
                    # fenced: never watch or adopt again — a zombie
                    # must not claim peer chains; the service notices
                    # via epoch_fence_check on its next persist
                    break
                self.poll()
            except Exception:  # noqa: BLE001 — storage hiccups must
                pass  # not kill the heartbeat loop; next tick retries
            self._stop.wait(self.heartbeat_s)

    def stop(self, retire: bool = True) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=max(5.0, self.heartbeat_s * 2))
        if retire:
            self.retire()

    # -- introspection --------------------------------------------------

    def child_guard(self) -> str:
        """The epoch guard shipped to isolated children via
        ``engine/subproc.py`` (``CHILD_EPOCH_ENV``): enough for the
        child to re-read the chain and discover a superseding epoch
        without importing any service machinery."""
        with self._lock:
            epoch = self.epoch
        return json.dumps(
            {
                "fleet_dir": self.fleet_dir,
                "replica": self.replica_id,
                "epoch": epoch,
            },
            sort_keys=True,
        )

    def snapshot(self) -> Dict[str, Any]:
        """The ``health()['fleet']`` payload: own lease, peer chains
        with ages on this replica's clock, adoption/fence history."""
        now = self._clock.now()
        peers: Dict[str, Any] = {}
        for chain_id, lease in self._chains().items():
            if chain_id == self.replica_id:
                continue
            seen = self._peer_seen.get(chain_id)
            peers[chain_id] = {
                "epoch": lease.epoch,
                "state": lease.state,
                "owner": lease.owner,
                "stale_for_s": (
                    round(now - seen[1], 3) if seen is not None else None
                ),
            }
        with self._lock:
            adoptions = [
                {
                    "replica": a.replica,
                    "epoch": a.epoch,
                    "journal_dir": a.journal_dir,
                    "stale_for_s": round(a.stale_for_s, 3),
                }
                for a in self._adoptions
            ]
            return {
                "replica": self.replica_id,
                "epoch": self.epoch,
                "fenced": self._fenced,
                "lease_timeout_s": self.lease_timeout_s,
                "heartbeat_s": self.heartbeat_s,
                "peers": peers,
                "adoptions": adoptions,
                "adoption_races_lost": self._races_lost,
            }


def epoch_fence_check(supervisor: Optional[FleetSupervisor]) -> bool:
    """THE persist-path guard (fence-discipline staticcheck rule): True
    when writing is allowed — no fleet configured, or this replica
    still owns its lease epoch. On a fence hit it counts and logs the
    suppressed write so zombie activity is visible on the health
    plane, then returns False: the caller must drop the persist (the
    adopter owns it now), not raise mid-flight."""
    if supervisor is None:
        return True
    if not supervisor.fenced():
        return True
    tm = get_telemetry()
    tm.counter("service.fleet.fenced_writes").inc()
    tm.event(
        "fleet_write_fenced",
        replica=supervisor.replica_id,
        epoch=supervisor.epoch,
    )
    return False
