"""VerificationSuite: the top user entry point.

Reference: ``src/main/scala/com/amazon/deequ/VerificationSuite.scala`` +
``VerificationResult.scala`` + ``VerificationRunBuilder.scala``
(SURVEY.md §2.5, §3.1): collect required analyzers from all checks,
delegate to AnalysisRunner (ONE fused scan + shared frequency passes),
evaluate each check against the AnalyzerContext (pure metric lookups),
aggregate statuses, export as records/JSON. Also the incremental variant
``run_on_aggregated_states`` and anomaly-check wiring (§3.5).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.runner import AnalysisRunner, AnalyzerContext
from deequ_tpu.checks.check import (
    Check,
    CheckLevel,
    CheckResult,
    CheckStatus,
)
from deequ_tpu.constraints.constraint import ConstraintStatus
from deequ_tpu.data.table import Dataset, Schema
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.metrics.metric import Metric


class VerificationResult:
    """Overall status + per-check results + all computed metrics."""

    def __init__(
        self,
        status: CheckStatus,
        check_results: Dict[Check, CheckResult],
        metrics: Dict[Analyzer, Metric],
        data: Optional[Dataset] = None,
    ):
        self.status = status
        self.check_results = check_results
        self.metrics = metrics
        self._data = data  # for row-level results; None on state-only runs
        self.run_metadata = None  # per-pass timings (set by the suite)
        self.telemetry = None  # telemetry run summary (set by the suite)
        # engine.resilience.ScanDegradation when the run's scans
        # quarantined batches; None = clean run (set by the suite)
        self.degradation = None
        # engine.deadline.ScanInterruption when the run was cancelled
        # or hit its deadline mid-scan — metrics are partial, the
        # overall status floors per config.degradation_policy, and
        # interruption.checkpointed says whether a resume cursor was
        # persisted; None = ran to completion (set by the suite)
        self.interruption = None
        # egress.EgressReport when the run streamed row-level outcomes
        # to a clean/quarantine split (row_level_sink=); None otherwise
        self.row_level_egress = None

    def row_level_results_as_dataset(
        self,
        data: Optional[Dataset] = None,
        filtered_row_outcome: str = "true",
    ) -> Dataset:
        """Per-row pass/fail per row-level-capable constraint (reference:
        rowLevelResultsAsDataFrame — SURVEY.md §2.2). Pass ``data``
        explicitly for runs evaluated from aggregated states.
        ``filtered_row_outcome``: "true" (where-excluded rows pass,
        default) or "null" (SQL NULL in a nullable boolean column) —
        the reference's AnalyzerOptions.filteredRow semantics."""
        from deequ_tpu.verification.rowlevel import row_level_results

        target = data if data is not None else self._data
        if target is None:
            raise ValueError(
                "row-level results need the dataset; this result was "
                "computed without one (state-only run) — pass data="
            )
        return row_level_results(
            self.check_results, target,
            filtered_row_outcome=filtered_row_outcome,
        )

    # -- exporters (reference: VerificationResult companion object) -----

    def success_metrics_as_records(self) -> List[Dict[str, Any]]:
        return AnalyzerContext(self.metrics).success_metrics_as_records()

    def success_metrics_as_json(self) -> str:
        return AnalyzerContext(self.metrics).success_metrics_as_json()

    def success_metrics_as_dataframe(self):
        return AnalyzerContext(self.metrics).success_metrics_as_dataframe()

    def check_results_as_records(self) -> List[Dict[str, Any]]:
        records = []
        for check, result in self.check_results.items():
            for cr in result.constraint_results:
                records.append(
                    {
                        "check": check.description,
                        "check_level": check.level.value,
                        "check_status": result.status.value,
                        "constraint": str(cr.constraint),
                        "constraint_status": cr.status.value,
                        "constraint_message": cr.message or "",
                    }
                )
        return records

    def check_results_as_json(self) -> str:
        return json.dumps(self.check_results_as_records(), indent=2)

    def check_results_as_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.check_results_as_records())


class VerificationSuite:
    def on_data(self, data: Dataset) -> "VerificationRunBuilder":
        return VerificationRunBuilder(data)

    @staticmethod
    def do_verification_run(
        data: Dataset,
        checks: Sequence[Check],
        required_analyzers: Sequence[Analyzer] = (),
        aggregate_with=None,
        save_states_with=None,
        engine: Optional[AnalysisEngine] = None,
        metrics_repository=None,
        reuse_existing_results_for_key=None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key=None,
        deadline=None,
        cancel=None,
        row_level_sink=None,
    ) -> VerificationResult:
        """Run all checks. ``deadline`` (seconds or a ``RunBudget``) and
        ``cancel`` (a ``CancelToken``) bound the run — an interrupt
        still returns a result: partial metrics, the overall status
        floored per ``config.degradation_policy``, and
        ``result.interruption`` carrying the provenance
        (docs/RESILIENCE.md, "Deadlines & cancellation").

        ``row_level_sink`` (an ``egress.RowLevelSink``): stream per-row
        pass/fail outcomes to a partitioned clean/quarantine parquet
        split INSIDE the same fused scan — ``result.row_level_egress``
        reports what was written (docs/EGRESS.md)."""
        analyzers = list(required_analyzers) + [
            a for check in checks for a in check.required_analyzers()
        ]
        sink_plan = None
        if row_level_sink is not None:
            from deequ_tpu.egress import finalize_row_sink, plan_row_sink

            engine = engine or AnalysisEngine()
            sink_plan = plan_row_sink(row_level_sink, checks, data, engine)
        try:
            context = AnalysisRunner.do_analysis_run(
                data,
                analyzers,
                aggregate_with=aggregate_with,
                save_states_with=save_states_with,
                engine=engine,
                metrics_repository=metrics_repository,
                reuse_existing_results_for_key=reuse_existing_results_for_key,
                fail_if_results_missing=fail_if_results_missing,
                save_or_append_results_with_key=save_or_append_results_with_key,
                deadline=deadline,
                cancel=cancel,
                row_sink=sink_plan,
            )
        except BaseException:
            if sink_plan is not None:
                sink_plan.mark_scan_failed()
                finalize_row_sink(sink_plan, data, engine)
            raise
        result = VerificationSuite.evaluate(checks, context, data=data)
        if row_level_sink is not None:
            if sink_plan is not None:
                result.row_level_egress = finalize_row_sink(
                    sink_plan, data, engine
                )
            else:
                result.row_level_egress = row_level_sink.report
        return result

    @staticmethod
    def do_coalesced_verification_run(
        data: Dataset,
        members: Sequence[Any],
        engine: Optional[AnalysisEngine] = None,
        deadline=None,
        cancel=None,
    ) -> List[VerificationResult]:
        """One scan, many suites: each member is a ``(checks,
        required_analyzers)`` pair; their analyzer sets are unioned
        into ONE superset analysis run (a single traversal of ``data``)
        and each member's checks are evaluated against its own sliced
        context (``AnalyzerContext.subset``) — metric-for-metric what a
        solo ``do_verification_run`` of that member would produce
        (pinned differentially in tests/test_coalesce.py). Returns one
        ``VerificationResult`` per member, in order; shared scan
        provenance (degradation/interruption/telemetry) rides every
        member's result. The service-side scan coalescer
        (docs/SERVICE.md "Scan coalescing") drives this."""
        suites = []
        for checks, required_analyzers in members:
            suites.append(
                list(required_analyzers)
                + [a for check in checks for a in check.required_analyzers()]
            )
        contexts = AnalysisRunner.do_coalesced_analysis_run(
            data, suites, engine=engine, deadline=deadline, cancel=cancel
        )
        return [
            VerificationSuite.evaluate(list(checks), context, data=data)
            for (checks, _), context in zip(members, contexts)
        ]

    @staticmethod
    def install_graceful_shutdown(signals=None):
        """Opt-in SIGTERM handling: maps process shutdown onto the
        process-wide shutdown ``CancelToken``, so every supervised run
        exits cleanly (final checkpoint, partial metrics) when the
        orchestrator says stop. Returns an ``uninstall()`` callable.
        See ``deequ_tpu.engine.deadline.install_graceful_shutdown``."""
        from deequ_tpu.engine.deadline import install_graceful_shutdown

        if signals is None:
            return install_graceful_shutdown()
        return install_graceful_shutdown(signals)

    @staticmethod
    def run_on_aggregated_states(
        schema: Schema,
        checks: Sequence[Check],
        state_loaders: Sequence[Any],
        required_analyzers: Sequence[Analyzer] = (),
        save_states_with=None,
    ) -> VerificationResult:
        analyzers = list(required_analyzers) + [
            a for check in checks for a in check.required_analyzers()
        ]
        context = AnalysisRunner.run_on_aggregated_states(
            schema, analyzers, state_loaders, save_states_with
        )
        return VerificationSuite.evaluate(checks, context)

    @staticmethod
    def evaluate(
        checks: Sequence[Check],
        context: AnalyzerContext,
        data: Optional[Dataset] = None,
    ) -> VerificationResult:
        from deequ_tpu.telemetry import get_telemetry

        tm = get_telemetry()
        check_results = {check: check.evaluate(context) for check in checks}
        if check_results:
            tm.counter("checks.evaluated").inc(len(check_results))
        for check, check_result in check_results.items():
            tm.check_evaluated(check, check_result)
        if not check_results:
            status = CheckStatus.SUCCESS
        else:
            worst = max(
                (r.status for r in check_results.values()),
                key=lambda s: ["Success", "Warning", "Error"].index(s.value),
            )
            status = worst
        # degraded scans (quarantined batches — docs/RESILIENCE.md):
        # metrics were computed over PARTIAL data, so the overall status
        # floors at whatever config.degradation_policy demands — "fail"
        # (default: partial data is an Error even if every check passed),
        # "warn" (surface but don't fail), or "tolerate" (status driven
        # by the checks alone; the record still rides the result)
        degradation = getattr(context, "degradation", None)
        # an interrupted run (cancelled / deadline-exceeded) also
        # computed its metrics over PARTIAL data — same policy floor as
        # quarantine: partial data is an Error under "fail", a Warning
        # under "warn", and check-driven under "tolerate"
        interruption = getattr(context, "interruption", None)
        if (
            degradation is not None and degradation.is_degraded
        ) or interruption is not None:
            from deequ_tpu import config

            policy = config.options().degradation_policy
            if policy not in ("fail", "warn", "tolerate"):
                raise ValueError(
                    f"config.degradation_policy must be 'fail', 'warn' "
                    f"or 'tolerate', got {policy!r}"
                )
            order = ["Success", "Warning", "Error"]
            floor = {
                "fail": CheckStatus.ERROR,
                "warn": CheckStatus.WARNING,
                "tolerate": status,
            }[policy]
            status = max(
                (status, floor), key=lambda s: order.index(s.value)
            )
            if degradation is not None and degradation.is_degraded:
                tm.counter("checks.degraded_runs").inc()
        result = VerificationResult(
            status, check_results, context.metric_map, data=data
        )
        result.run_metadata = context.run_metadata
        result.telemetry = context.telemetry
        result.degradation = degradation
        result.interruption = interruption
        return result


class VerificationRunBuilder:
    """Fluent builder (reference: VerificationRunBuilder.scala)."""

    def __init__(self, data: Dataset):
        self._data = data
        self._checks: List[Check] = []
        self._required_analyzers: List[Analyzer] = []
        self._engine: Optional[AnalysisEngine] = None
        self._aggregate_with = None
        self._save_states_with = None
        self._repository = None
        self._reuse_key = None
        self._fail_if_results_missing = False
        self._save_key = None
        self._anomaly_checks: List = []
        self._deadline = None
        self._cancel = None
        self._row_level_sink = None

    def add_check(self, check: Check) -> "VerificationRunBuilder":
        self._checks.append(check)
        return self

    def add_checks(self, checks: Sequence[Check]) -> "VerificationRunBuilder":
        self._checks.extend(checks)
        return self

    def add_required_analyzer(self, analyzer: Analyzer) -> "VerificationRunBuilder":
        self._required_analyzers.append(analyzer)
        return self

    def add_required_analyzers(
        self, analyzers: Sequence[Analyzer]
    ) -> "VerificationRunBuilder":
        self._required_analyzers.extend(analyzers)
        return self

    def with_engine(self, engine: AnalysisEngine) -> "VerificationRunBuilder":
        self._engine = engine
        return self

    def with_deadline(self, deadline) -> "VerificationRunBuilder":
        """Bound the run: seconds (float) or a full ``RunBudget``."""
        self._deadline = deadline
        return self

    def with_cancel(self, cancel) -> "VerificationRunBuilder":
        """Attach a ``CancelToken`` — cancelling it mid-run exits the
        scan cleanly with partial metrics + a resumable checkpoint."""
        self._cancel = cancel
        return self

    def with_row_level_sink(self, sink) -> "VerificationRunBuilder":
        """Stream per-row pass/fail outcomes to a clean/quarantine
        parquet split (an ``egress.RowLevelSink``) inside the same
        fused scan — docs/EGRESS.md."""
        self._row_level_sink = sink
        return self

    def aggregate_with(self, state_loader) -> "VerificationRunBuilder":
        self._aggregate_with = state_loader
        return self

    def save_states_with(self, state_persister) -> "VerificationRunBuilder":
        self._save_states_with = state_persister
        return self

    def use_repository(self, repository) -> "VerificationRunBuilder":
        self._repository = repository
        return self

    def reuse_existing_results_for_key(
        self, key, fail_if_results_missing: bool = False
    ) -> "VerificationRunBuilder":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key) -> "VerificationRunBuilder":
        self._save_key = key
        return self

    def add_anomaly_check(
        self,
        strategy,
        analyzer: Analyzer,
        anomaly_check_config=None,
    ) -> "VerificationRunBuilder":
        """Wire a metric-series anomaly check (reference: §3.5): the
        synthesized check's assertion loads the metric history from the
        repository and asks the strategy whether the new point is
        anomalous."""
        if self._repository is None:
            raise ValueError(
                "add_anomaly_check requires use_repository(...) first"
            )
        from deequ_tpu.anomalydetection.wiring import AnomalyCheckConfig

        config = anomaly_check_config or AnomalyCheckConfig(
            level=CheckLevel.WARNING,
            description=f"Anomaly check for {analyzer.name}({analyzer.instance})",
        )
        self._anomaly_checks.append((strategy, analyzer, config))
        return self

    def run(self) -> VerificationResult:
        checks = list(self._checks)
        for strategy, analyzer, config in self._anomaly_checks:
            from deequ_tpu.anomalydetection.wiring import build_anomaly_check

            checks.append(
                build_anomaly_check(
                    self._repository, strategy, analyzer, config,
                    current_key=self._save_key,
                )
            )
        return VerificationSuite.do_verification_run(
            self._data,
            checks,
            required_analyzers=self._required_analyzers,
            aggregate_with=self._aggregate_with,
            save_states_with=self._save_states_with,
            engine=self._engine,
            metrics_repository=self._repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_or_append_results_with_key=self._save_key,
            deadline=self._deadline,
            cancel=self._cancel,
            row_level_sink=self._row_level_sink,
        )
