"""JSON serde for analysis results.

Reference: ``repository/AnalysisResultSerde.scala`` (SURVEY.md §2.5) —
custom serializers for every metric type (incl. Distribution and KLL
buckets) plus full analyzer descriptors, so persisted series are
self-describing and reloadable. Analyzers here serialize from their
dataclass fields into a {type, **params} object resolved against a
registry on load.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Type

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    ColumnCount,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    CustomSql,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    RatioOfSums,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.base import (
    Analyzer,
    MetricCalculationRuntimeException,
)
from deequ_tpu.analyzers.runner import AnalyzerContext
from deequ_tpu.metrics.distribution import (
    Distribution,
    DistributionValue,
    HistogramMetric,
)
from deequ_tpu.metrics.kll import BucketDistribution, BucketValue, KLLMetric
from deequ_tpu.metrics.metric import (
    DoubleMetric,
    Entity,
    KeyedDoubleMetric,
    Metric,
)
from deequ_tpu.repository.base import AnalysisResult, ResultKey
from deequ_tpu.sketches.kll import KLLParameters
from deequ_tpu.telemetry.oprecords import OperationalAnalyzer
from deequ_tpu.utils.trylike import Failure, Success

ANALYZER_REGISTRY: Dict[str, Type[Analyzer]] = {
    cls.__name__: cls
    for cls in (
        ApproxCountDistinct,
        ApproxQuantile,
        ApproxQuantiles,
        ColumnCount,
        Completeness,
        Compliance,
        Correlation,
        CountDistinct,
        CustomSql,
        DataType,
        Distinctness,
        Entropy,
        Histogram,
        KLLSketch,
        Maximum,
        MaxLength,
        Mean,
        Minimum,
        MinLength,
        MutualInformation,
        PatternMatch,
        RatioOfSums,
        Size,
        StandardDeviation,
        Sum,
        Uniqueness,
        UniqueValueRatio,
        # telemetry's repository-persisted operational records ride the
        # same serde path as data-quality metrics
        OperationalAnalyzer,
    )
}


def _param_to_json(value: Any) -> Any:
    if isinstance(value, KLLParameters):
        return {
            "__kll_params__": True,
            "sketch_size": value.sketch_size,
            "shrinking_factor": value.shrinking_factor,
            "number_of_buckets": value.number_of_buckets,
        }
    if isinstance(value, tuple):
        return list(value)
    return value


def _param_from_json(value: Any) -> Any:
    if isinstance(value, dict) and value.get("__kll_params__"):
        return KLLParameters(
            value["sketch_size"],
            value["shrinking_factor"],
            value["number_of_buckets"],
        )
    if isinstance(value, list):
        return tuple(value)
    return value


def analyzer_to_json(analyzer: Analyzer) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": type(analyzer).__name__}
    for f in dataclasses.fields(analyzer):
        out[f.name] = _param_to_json(getattr(analyzer, f.name))
    return out


def analyzer_from_json(data: Dict[str, Any]) -> Analyzer:
    cls = ANALYZER_REGISTRY.get(data["type"])
    if cls is None:
        raise ValueError(f"unknown analyzer type {data['type']!r}")
    kwargs = {
        k: _param_from_json(v) for k, v in data.items() if k != "type"
    }
    return cls(**kwargs)


def metric_to_json(metric: Metric) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "metric_type": type(metric).__name__,
        "entity": metric.entity.value,
        "name": metric.name,
        "instance": metric.instance,
    }
    if metric.value.is_failure:
        out["error"] = str(metric.value.exception)
        return out
    value = metric.value.get()
    if isinstance(metric, DoubleMetric):
        out["value"] = value
    elif isinstance(metric, KeyedDoubleMetric):
        out["value"] = dict(value)
    elif isinstance(metric, HistogramMetric):
        out["value"] = {
            "number_of_bins": value.number_of_bins,
            "values": {
                k: {"absolute": dv.absolute, "ratio": dv.ratio}
                for k, dv in value.values.items()
            },
        }
    elif isinstance(metric, KLLMetric):
        out["value"] = {
            "buckets": [
                {
                    "low_value": b.low_value,
                    "high_value": b.high_value,
                    "count": b.count,
                }
                for b in value.buckets
            ],
            "parameters": list(value.parameters),
            "data": [list(level) for level in value.data],
        }
    else:
        raise TypeError(f"cannot serialize metric type {type(metric)}")
    return out


def metric_from_json(data: Dict[str, Any]) -> Metric:
    entity = Entity(data["entity"])
    name = data["name"]
    instance = data["instance"]
    metric_type = data["metric_type"]
    if "error" in data:
        value = Failure(MetricCalculationRuntimeException(data["error"]))
        cls = {
            "DoubleMetric": DoubleMetric,
            "KeyedDoubleMetric": KeyedDoubleMetric,
            "HistogramMetric": HistogramMetric,
            "KLLMetric": KLLMetric,
        }[metric_type]
        return cls(entity, name, instance, value)
    raw = data["value"]
    if metric_type == "DoubleMetric":
        return DoubleMetric(entity, name, instance, Success(float(raw)))
    if metric_type == "KeyedDoubleMetric":
        return KeyedDoubleMetric(entity, name, instance, Success(dict(raw)))
    if metric_type == "HistogramMetric":
        dist = Distribution(
            {
                k: DistributionValue(v["absolute"], v["ratio"])
                for k, v in raw["values"].items()
            },
            raw["number_of_bins"],
        )
        return HistogramMetric(entity, name, instance, Success(dist))
    if metric_type == "KLLMetric":
        dist = BucketDistribution(
            [
                BucketValue(b["low_value"], b["high_value"], b["count"])
                for b in raw["buckets"]
            ],
            tuple(raw["parameters"]),
            tuple(tuple(level) for level in raw["data"]),
        )
        return KLLMetric(entity, name, instance, Success(dist))
    raise TypeError(f"unknown metric type {metric_type!r}")


def serialize(results: List[AnalysisResult], indent: int = 2) -> str:
    payload = []
    for result in results:
        payload.append(
            {
                "result_key": {
                    "dataset_date": result.result_key.dataset_date,
                    "tags": result.result_key.tags_dict,
                },
                "analyzer_context": [
                    {
                        "analyzer": analyzer_to_json(a),
                        "metric": metric_to_json(m),
                    }
                    for a, m in result.analyzer_context.metric_map.items()
                ],
            }
        )
    return json.dumps(payload, indent=indent)


def deserialize(text: str) -> List[AnalysisResult]:
    payload = json.loads(text)
    out: List[AnalysisResult] = []
    for entry in payload:
        key = ResultKey.of(
            entry["result_key"]["dataset_date"],
            entry["result_key"]["tags"],
        )
        metric_map = {}
        for pair in entry["analyzer_context"]:
            analyzer = analyzer_from_json(pair["analyzer"])
            metric_map[analyzer] = metric_from_json(pair["metric"])
        out.append(AnalysisResult(key, AnalyzerContext(metric_map)))
    return out
