from deequ_tpu.engine.scan import AnalysisEngine, monoid_all_reduce

__all__ = ["AnalysisEngine", "monoid_all_reduce"]
