"""``metric-docs``: the registered-metric <-> docs-catalog contract.

Every counter/gauge/histogram name registered anywhere in ``deequ_tpu/``
(a literal or f-string first argument to a ``.counter(...)`` /
``.gauge(...)`` / ``.histogram(...)`` call, or to the repository's
``_bump(...)`` wrapper) must have a row in the "## Metric catalog"
section of docs/OBSERVABILITY.md — and every catalogued name must
still be registered somewhere, so the catalog cannot rot into
describing metrics that no longer exist.

Name normalization: an f-string hole (``f"...per_shape.{label}.hits"``)
and a docs placeholder (```engine...per_shape.<label>.hits```) both
become ``*`` segments, so dynamic families match their one catalog row.
Dynamic names built any other way (a plain variable argument) are
invisible to this rule — register through a literal/f-string or
document the family at its call site.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Sequence, Tuple

from tools.staticcheck.core import Analyzer, Finding, SourceFile, register

DOCS_REL = "docs/OBSERVABILITY.md"
CATALOG_HEADING = "## Metric catalog"

_REGISTRY_ATTRS = frozenset({"counter", "gauge", "histogram"})
_WRAPPER_NAMES = frozenset({"_bump"})

_BACKTICK_RE = re.compile(r"`([^`]+)`")
_PLACEHOLDER_RE = re.compile(r"<[^>]+>")


def _literal_metric_name(node: ast.AST) -> str:
    """The metric name of a call's first argument: a string literal
    verbatim, an f-string with every hole collapsed to ``*``, else ''
    (not statically resolvable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for piece in node.values:
            if isinstance(piece, ast.Constant) and isinstance(
                piece.value, str
            ):
                parts.append(piece.value)
            else:
                parts.append("*")
        return "".join(parts)
    return ""


def _looks_like_metric(name: str) -> bool:
    """Filter out non-metric string arguments that happen to reach a
    same-named method: catalogued names are dotted lowercase paths."""
    return bool(name) and "." in name and " " not in name


def collect_registrations(
    files: Sequence[SourceFile],
) -> Dict[str, List[Tuple[str, int]]]:
    """{normalized metric name: [(rel path, line), ...]} over every
    statically-resolvable registration site in the scanned tree."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr not in _REGISTRY_ATTRS:
                    continue
            elif isinstance(func, ast.Name):
                if func.id not in _WRAPPER_NAMES:
                    continue
            else:
                continue
            name = _literal_metric_name(node.args[0])
            if not _looks_like_metric(name):
                continue
            out.setdefault(name, []).append((sf.rel, node.lineno))
    return out


def parse_catalog(text: str) -> Dict[str, int]:
    """{normalized metric name: line} from the backticked first cell
    of each table row inside the "## Metric catalog" section (the
    section ends at the next ``## `` heading)."""
    out: Dict[str, int] = {}
    in_section = False
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if stripped.startswith("## "):
            in_section = stripped.startswith(CATALOG_HEADING)
            continue
        if not in_section or not stripped.startswith("|"):
            continue
        match = _BACKTICK_RE.search(stripped)
        if match is None:
            continue
        name = _PLACEHOLDER_RE.sub("*", match.group(1)).strip()
        if _looks_like_metric(name):
            out.setdefault(name, lineno)
    return out


class MetricDocsAnalyzer(Analyzer):
    name = "metricdocs"
    rules = ("metric-docs",)
    description = (
        "every registered counter/gauge/histogram has a row in the "
        "docs/OBSERVABILITY.md metric catalog, and vice versa"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        registered = collect_registrations(files)
        docs_path = os.path.join(root, DOCS_REL.replace("/", os.sep))
        if not os.path.isfile(docs_path):
            # a tree with no metric registrations has no contract to
            # enforce (fixture roots); one with registrations must
            # carry the catalog
            if registered:
                yield Finding(
                    rule="metric-docs",
                    path=DOCS_REL,
                    line=0,
                    message=f"{DOCS_REL} is missing — the metric "
                    "catalog lives there",
                )
            return
        with open(docs_path, "r", encoding="utf-8") as fh:
            text = fh.read()
        documented = parse_catalog(text)
        if not documented:
            if registered:
                yield Finding(
                    rule="metric-docs",
                    path=DOCS_REL,
                    line=0,
                    message=f'no "{CATALOG_HEADING}" table rows found '
                    f"in {DOCS_REL}",
                )
            return
        for name in sorted(registered):
            if name in documented:
                continue
            rel, line = registered[name][0]
            yield Finding(
                rule="metric-docs",
                path=rel,
                line=line,
                message=(
                    f"metric '{name}' is registered here but has no "
                    f'row in the {DOCS_REL} "{CATALOG_HEADING}" table'
                ),
                symbol=name,
            )
        for name in sorted(documented):
            if name in registered:
                continue
            yield Finding(
                rule="metric-docs",
                path=DOCS_REL,
                line=documented[name],
                message=(
                    f"catalog row for '{name}' has no registration "
                    "site anywhere in deequ_tpu/ — stale docs"
                ),
                symbol=name,
            )


register(MetricDocsAnalyzer())
